"""Shard-count scaling of keyspace ingest — the million-key tier's
throughput story, measured.

Per-dispatch ingest cost scales with PLANE CAPACITY (the jitted merge
walks capacity-sized planes, not just the batch).  The sharded keyspace
(crdt_tpu.keyspace) carves one K-slot tenant universe into S independent
shards of K/S slots each, so a batch that lands whole in its owning
shard costs a K/S-sized dispatch instead of a K-sized one.  Every arm
drives N/B full dispatches at the SAME batch size (plus at most one
partial tail per shard run, reported per row) — only the per-shard
capacity changes — so the wall-clock ratio isolates the capacity term:
near-linear throughput in S until fixed dispatch overhead dominates.
On CPU jax the capacity term measures ~1.1 us/slot against a ~1 ms
fixed dispatch floor, so the gate needs K/S well above ~4K slots —
exactly the regime the million-key tier runs in.

The client is shard-aligned, which is the system's intended write path:
rendezvous routing is deterministic across processes (the routing
property tests pin this), so a producer partitions its stream with the
same hash the server uses — the keyspace analogue of partition-aware
producers — and each admitted group drains as ONE dispatch into ONE
shard.  A shard-oblivious client still converges identically; it just
pays splits at the door instead of at the producer.

Two phases:

* **parity** — one multi-tenant stream through an S=4 keyspace door:
  per-tenant views must equal the client-side fold exactly, dispatch
  counts are pinned (N/B, not just reported), and a second, freshly
  built keyspace fed each shard's gossip payload must converge
  bit-identical per shard (routing determinism + shard-scoped
  anti-entropy, end to end).
* **scaling** — arms S in {1, 2, 4} over a FIXED total capacity K and
  the identical stream: per-shard capacity K/S, batch size B, N/B
  dispatches per arm; rep 0 of each arm is an uncounted warm-up that
  absorbs jit compilation for that arm's K/S shapes.  The gate
  (--assert-scaling) requires wps_S >= eff * S * wps_1 for S=4.

Methodology (house rules, benches/bench_baseline.py): medians over reps,
JSON rows on stdout.

Usage:
  python benches/bench_keyspace.py                        # default shape
  python benches/bench_keyspace.py --tiny                 # CI smoke
  python benches/bench_keyspace.py --assert-scaling 0.75  # gate 1->4
"""
from __future__ import annotations

import argparse
import json
import pathlib
import statistics
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

#: scaling arms: shard counts over one fixed total capacity
ARMS = (1, 2, 4)

#: parity-phase tenants (the scaling arms use one tenant: isolation is
#: the soak's oracle, capacity is what this bench isolates)
TENANTS = ("t-acme", "t-bolt", "t-crab", "t-dune")


def _stream(n_ops: int, seed: int, tenants=("bench",)):
    """Seeded (tenant, key, value) stream over a simulated million-key
    universe: unique keys (coprime stride walk) so the fold oracle has
    no LWW ties to model."""
    import random

    rng = random.Random(seed)
    out = []
    for i in range(n_ops):
        idx = (i * 999_983) % 1_000_000
        out.append((tenants[rng.randrange(len(tenants))],
                    f"u{idx:06d}", f"v{idx:06d}"))
    return out


def _fresh_door(n_shards: int, total_capacity: int, batch: int):
    from crdt_tpu.keyspace import KeyspaceFrontDoor, ShardedKeyspace

    ks = ShardedKeyspace(rid=0, n_shards=n_shards,
                         capacity=total_capacity // n_shards)
    # max_batch == the submission group size: every full shard-aligned
    # group trips the size drain inline on the submitting thread, so the
    # timed region measures drain cost (one jitted dispatch per group);
    # the few partial tail groups self-flush on a tight deadline
    door = KeyspaceFrontDoor(ks, max_batch=batch, flush_deadline_s=0.002)
    return ks, door


def _partition(stream, ks, batch: int):
    """Client-side shard alignment OUTSIDE the timed region: the same
    rendezvous hash the server uses splits the stream per shard, then
    chunks each shard's run into batch-sized admission groups."""
    runs = {}
    for tenant, key, value in stream:
        runs.setdefault((ks.shard_of(tenant, key), tenant),
                        []).append((key, value))
    groups = []
    for (_, tenant), rows in runs.items():
        for i in range(0, len(rows), batch):
            groups.append((tenant, dict(rows[i:i + batch])))
    return groups


def _dispatches(ks) -> int:
    return sum(
        int(shard.metrics.registry.counter_value("merge_dispatches"))
        for shard in ks.shards)


def _run_arm(groups, n_shards: int, total_capacity: int, batch: int):
    ks, door = _fresh_door(n_shards, total_capacity, batch)
    t0 = time.perf_counter()
    for tenant, cmd in groups:
        door.admit_cmd(tenant, cmd, timeout=30.0)
    wall = time.perf_counter() - t0
    return ks, wall


def _check_parity(stream, total_capacity: int, batch: int) -> int:
    """S=4 parity: per-tenant fold equality, pinned dispatch count, and
    bit-identical per-shard convergence into a second keyspace."""
    n_shards = 4
    ks, door = _fresh_door(n_shards, total_capacity, batch)
    expected = {t: {} for t in TENANTS}
    for tenant, key, value in stream:
        expected[tenant][key] = value
    groups = _partition(stream, ks, batch)
    for tenant, cmd in groups:
        idents = door.admit_cmd(tenant, cmd, timeout=30.0)
        assert all(i is not None for i in idents), "lost idents"
    for tenant in TENANTS:
        got = ks.tenant_state(tenant)
        assert got == expected[tenant], (
            f"tenant {tenant!r} view != client fold: "
            f"missing={sorted(set(expected[tenant]) - set(got))[:5]} "
            f"extra={sorted(set(got) - set(expected[tenant]))[:5]}")
    n_groups = len(groups)
    assert _dispatches(ks) == n_groups, (
        f"{_dispatches(ks)} dispatches for {n_groups} shard-aligned "
        "groups: drain fusion broken")
    # shard-scoped anti-entropy into a freshly built twin: routing
    # determinism means shard i's payload rebuilds shard i exactly
    from crdt_tpu.keyspace import ShardedKeyspace

    twin = ShardedKeyspace(rid=0, n_shards=n_shards,
                           capacity=total_capacity // n_shards)
    for i in range(n_shards):
        twin.receive(i, ks.gossip_payload(i, None))
        assert twin.shards[i].get_state() == ks.shards[i].get_state(), (
            f"shard {i} state diverged after full-payload receive")
        assert (twin.shards[i].version_vector()
                == ks.shards[i].version_vector()), (
            f"shard {i} vv diverged after full-payload receive")
    return n_groups


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n-ops", type=int, default=8_192,
                    help="scaling-phase stream length (all arms)")
    ap.add_argument("--capacity", type=int, default=65_536,
                    help="TOTAL keyspace capacity, split across shards")
    ap.add_argument("--batch", type=int, default=128,
                    help="shard-aligned admission group size")
    ap.add_argument("--n-parity", type=int, default=2_048,
                    help="parity-phase stream length")
    ap.add_argument("--reps", type=int, default=3,
                    help="measured reps per arm (plus one warm-up)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tiny", action="store_true",
                    help="CI smoke: 2K-op arms over 64K total capacity")
    ap.add_argument("--assert-scaling", type=float, nargs="?",
                    const=0.75, default=None, metavar="EFF",
                    help="exit nonzero unless the 4-shard arm reaches "
                         "EFF x ideal (wps_4 >= EFF * 4 * wps_1); "
                         "default EFF 0.75")
    args = ap.parse_args()
    if args.tiny:
        # total capacity stays HIGH even in tiny mode: the scaling
        # signal lives in the capacity term, and shrinking K below
        # ~16K/shard drowns it in the fixed dispatch floor
        args.n_ops, args.capacity, args.batch = 2_048, 65_536, 64
        args.n_parity, args.reps = 512, 2

    rows = []

    # ---- phase 1: parity (fold equality, pinned dispatches, twin) ----
    parity_stream = _stream(args.n_parity, args.seed, tenants=TENANTS)
    n_groups = _check_parity(parity_stream, args.capacity, args.batch)
    rows.append({"phase": "parity", "n_ops": args.n_parity,
                 "n_shards": 4, "groups": n_groups,
                 "fold_exact": True, "twin_bit_identical": True})

    # ---- phase 2: scaling over a fixed total capacity ----
    stream = _stream(args.n_ops, args.seed)
    assert args.n_ops % args.batch == 0, "n_ops must divide by batch"
    walls = {}
    for n_shards in ARMS:
        # partition against a throwaway keyspace (routing depends only
        # on the shard count, so any same-S instance agrees)
        ks0, _ = _fresh_door(n_shards, args.capacity, args.batch)
        groups = _partition(stream, ks0, args.batch)
        arm_walls = []
        for rep in range(args.reps + 1):  # rep 0 = uncounted warm-up
            ks, wall = _run_arm(groups, n_shards, args.capacity,
                                args.batch)
            assert _dispatches(ks) == len(groups), (
                f"S={n_shards}: {_dispatches(ks)} dispatches for "
                f"{len(groups)} groups")
            total_keys = sum(st["keys"] for st in ks.shard_stats())
            assert total_keys == len({k for _, k, _ in stream}), (
                f"S={n_shards}: {total_keys} keys materialized")
            if rep == 0:
                continue
            arm_walls.append(wall)
            rows.append({"phase": "scaling", "n_shards": n_shards,
                         "rep": rep, "wall_s": round(wall, 4),
                         "dispatches": len(groups),
                         "shard_capacity": args.capacity // n_shards})
        walls[n_shards] = statistics.median(arm_walls)

    wps = {s: args.n_ops / walls[s] for s in ARMS}
    eff = {s: wps[s] / (s * wps[1]) for s in ARMS}
    summary = {
        "bench": "keyspace",
        "n_ops": args.n_ops, "total_capacity": args.capacity,
        "batch": args.batch, "reps": args.reps,
        **{f"wall_s{s}_median_s": round(walls[s], 4) for s in ARMS},
        **{f"writes_per_s_s{s}": round(wps[s]) for s in ARMS},
        **{f"scaling_eff_s{s}": round(eff[s], 3) for s in ARMS},
        "speedup_1_to_4": round(wps[4] / wps[1], 2),
        "parity_exact": True,  # parity phase would have raised
    }
    for row in rows:
        print(json.dumps(row))
    print(json.dumps(summary))
    if args.assert_scaling is not None and eff[4] < args.assert_scaling:
        print(f"FAIL: 4-shard scaling efficiency {eff[4]:.3f} < "
              f"{args.assert_scaling} x ideal", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
