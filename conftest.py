"""Pytest bootstrap: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip TPU hardware is not available in CI; all mesh/sharding tests run on
8 virtual CPU devices (the driver separately dry-run-compiles the multi-chip
path via __graft_entry__.dryrun_multichip).  These env vars must be set before
the first `import jax` anywhere in the test process.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the ambient TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup, so the
# env vars above are too late for jax.config's env-read defaults — but the
# backend itself is initialized lazily, so a config update still lands.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_addoption(parser):
    parser.addoption(
        "--long",
        action="store_true",
        default=False,
        help="run the scaled-up fuzz schedules (50+ seeds x 500+ writes; "
        "see tests/test_parity_fuzz.py and PARITY.md).  CRDT_LONG=1 in the "
        "environment does the same for bare `pytest` invocations.",
    )


def pytest_configure(config):
    if os.environ.get("CRDT_LONG"):
        config.option.long = True
    config.addinivalue_line(
        "markers",
        "slow: long-running end-to-end checks (tier-1 runs -m 'not slow')",
    )
