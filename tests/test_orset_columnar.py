"""Columnar OR-Set fast path vs the generic per-set join (interpret mode)."""
import numpy as np
import pytest

from crdt_tpu.models import orset
from crdt_tpu.utils.constants import SENTINEL_PY


def _rand_sets(rng, n, cap=16):
    out = []
    for r in range(n):
        s = orset.empty(cap)
        for i in range(int(rng.integers(1, 6))):
            s = orset.add(s, int(rng.integers(0, 10)), r % 64, i)
            if rng.random() < 0.3:
                s = orset.remove(s, int(rng.integers(0, 10)))
        out.append(s)
    return out


def _lane(packed, removed, j):
    return [
        (int(k), int(v))
        for k, v in zip(np.asarray(packed)[:, j], np.asarray(removed)[:, j])
        if k != SENTINEL_PY
    ]


def test_columnar_join_matches_generic_every_lane():
    rng = np.random.default_rng(1)
    a_sets = _rand_sets(rng, 128)
    b_sets = _rand_sets(rng, 128)
    pa, ra = orset.stack_to_columnar(a_sets)
    pb, rb = orset.stack_to_columnar(b_sets)
    pk, rm, n = orset.columnar_join(pa, ra, pb, rb, out_size=32, interpret=True)

    for j in range(128):
        g = orset.join(a_sets[j], b_sets[j])
        pg, rg = orset.stack_to_columnar(g)
        assert _lane(pk, rm, j) == _lane(pg, rg, 0), f"lane {j}"
        assert int(np.asarray(n)[j]) == len(_lane(pg, rg, 0))


def test_columnar_member_mask_matches_generic():
    rng = np.random.default_rng(2)
    sets = _rand_sets(rng, 128)
    p, r = orset.stack_to_columnar(sets)
    mask = np.asarray(orset.columnar_member_mask(p, r, 10))
    for j in range(0, 128, 13):
        expect = np.asarray(orset.member_mask(sets[j], 10))
        assert (mask[:, j] == expect).all(), f"lane {j}"


def test_columnar_join_pads_non_tile_lane_counts():
    rng = np.random.default_rng(5)
    sets_a, sets_b = _rand_sets(rng, 5), _rand_sets(rng, 5)  # 5 lanes != 128k
    pa, ra = orset.stack_to_columnar(sets_a)
    pb, rb = orset.stack_to_columnar(sets_b)
    pk, rm, n = orset.columnar_join(pa, ra, pb, rb, out_size=32, interpret=True)
    assert pk.shape[1] == 5
    for j in range(5):
        g = orset.join(sets_a[j], sets_b[j])
        pg, rg = orset.stack_to_columnar(g)
        assert _lane(pk, rm, j) == _lane(pg, rg, 0), f"lane {j}"


def test_stack_to_columnar_rejects_out_of_budget_tags():
    s = orset.empty(8)
    s = orset.add(s, elem=1, rid=999, seq=0)  # rid budget is 6 bits
    with pytest.raises(ValueError):
        orset.stack_to_columnar(s)
