"""Delta gossip + stable-frontier compaction tests.

The reference never prunes its op log and re-ships the whole log every round
(/root/reference/main.go:75, main.go:159 — SURVEY.md §6 "unbounded growth");
crdt_tpu.models.compactlog bounds both.  These tests check the two contracts
that make that sound:

* delta extraction is lossless: merging a vv-filtered delta equals merging
  the full log;
* compaction is observably transparent: rebuild() is invariant under any
  sanctioned frontier advance, across merges, gossip, and fault injection.

Version vectors assume per-writer contiguous seqs (crdt_tpu.utils.clock
.SeqGen), so the generators here build writer histories as prefixes —
helpers.rand_ops's free-form (rid, seq) pairs would violate the invariant.
"""
import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.models import compactlog, oplog
from crdt_tpu.parallel import swarm
from tests.helpers import tree_equal

W = 3   # writers
K = 8   # interned key space
CAP = 64


def writer_histories(rng, n_writers=W, max_per_writer=8, n_keys=K):
    """Per-writer op columns: seq contiguous from 0, ts strictly increasing
    with seq (as a real node's clock+SeqGen produce)."""
    cols = {n: [] for n in ("ts", "rid", "seq", "key", "val", "payload", "is_num")}
    for w in range(n_writers):
        n_w = int(rng.integers(1, max_per_writer + 1))
        for s in range(n_w):
            cols["ts"].append(10 * s + w)  # unique + per-writer monotone
            cols["rid"].append(w)
            cols["seq"].append(s)
            cols["key"].append(int(rng.integers(0, n_keys)))
            is_num = bool(rng.random() < 0.7)
            cols["val"].append(int(rng.integers(-20, 21)) if is_num else 0)
            cols["payload"].append(int(rng.integers(0, 100)))
            cols["is_num"].append(is_num)
    return {
        n: np.asarray(c, bool if n == "is_num" else np.int32)
        for n, c in cols.items()
    }


def prefix_log(ops, prefix_per_writer, capacity=CAP):
    """A replica's log: the given per-writer prefix of each history."""
    keep = ops["seq"] < np.asarray(prefix_per_writer)[ops["rid"]]
    return oplog.from_ops(capacity, {k: v[keep] for k, v in ops.items()})


def rand_prefixes(rng, ops, n_writers=W):
    return [
        int(rng.integers(0, int((ops["rid"] == w).sum()) + 1))
        for w in range(n_writers)
    ]


# ---- version vectors + delta extraction ----


def test_version_vector_matches_numpy():
    rng = np.random.default_rng(0)
    ops = writer_histories(rng)
    pre = rand_prefixes(rng, ops)
    log = prefix_log(ops, pre)
    vv = np.asarray(oplog.version_vector(log, W))
    assert vv.tolist() == [p - 1 for p in pre]


def test_foreign_rid_rows_never_covered():
    # Go-peer ops arrive with rid = -1 (crdt_tpu.api.node) — no watermark.
    ops = {
        "ts": np.asarray([5], np.int32),
        "rid": np.asarray([-1], np.int32),
        "seq": np.asarray([0], np.int32),
        "key": np.asarray([2], np.int32),
        "val": np.asarray([7], np.int32),
        "payload": np.asarray([0], np.int32),
        "is_num": np.asarray([True], bool),
    }
    log = oplog.from_ops(8, ops)
    assert np.asarray(oplog.version_vector(log, W)).tolist() == [-1] * W
    vv = jnp.full((W,), 100, jnp.int32)
    assert not bool(oplog.covered_by(log, vv)[0])
    assert int(oplog.size(oplog.delta_since(log, vv))) == 1


def test_delta_since_is_lossless():
    """merge(a, delta_since(b, vv(a))) == merge(a, b) — the delta-gossip
    payload carries exactly what the receiver is missing."""
    rng = np.random.default_rng(1)
    for trial in range(10):
        ops = writer_histories(rng)
        a = prefix_log(ops, rand_prefixes(rng, ops))
        b = prefix_log(ops, rand_prefixes(rng, ops))
        vv_a = oplog.version_vector(a, W)
        delta = oplog.delta_since(b, vv_a)
        assert tree_equal(oplog.merge(a, delta), oplog.merge(a, b))
        # and the delta is disjoint from a's knowledge
        assert int(jnp.sum(oplog.covered_by(delta, vv_a))) == 0


# ---- compaction transparency ----


def _rand_stable_frontier(rng, *logs):
    """A frontier every given log can fold (≤ the min received vv) —
    what swarm.stable_frontier produces for this replica set."""
    vvs = np.stack([np.asarray(oplog.version_vector(l, W)) for l in logs])
    lo = vvs.min(axis=0)
    return jnp.asarray(
        [int(rng.integers(-1, lo[w] + 1)) if lo[w] >= 0 else -1 for w in range(W)],
        jnp.int32,
    )


def test_rebuild_invariant_under_compaction():
    rng = np.random.default_rng(2)
    for trial in range(10):
        ops = writer_histories(rng)
        log = prefix_log(ops, rand_prefixes(rng, ops))
        want = oplog.rebuild(log, K)
        c = compactlog.fresh(log, K, W)
        f1 = _rand_stable_frontier(rng, log)
        c1 = compactlog.compact(c, f1)
        assert tree_equal(compactlog.rebuild(c1), want)
        # a second, further advance over the already-compacted state
        c2 = compactlog.compact(c1, oplog.version_vector(log, W))
        assert tree_equal(compactlog.rebuild(c2), want)
        # fully folded: the tail is empty, state lives in the summary
        assert int(compactlog.size(c2)) == 0


def test_compact_clamps_to_received():
    """A frontier beyond this replica's knowledge must not advance past it
    (it would make merges drop never-received ops as already-folded)."""
    rng = np.random.default_rng(3)
    ops = writer_histories(rng)
    log = prefix_log(ops, rand_prefixes(rng, ops))
    c = compactlog.compact(
        compactlog.fresh(log, K, W), jnp.full((W,), 10_000, jnp.int32)
    )
    assert np.array_equal(
        np.asarray(c.frontier), np.asarray(oplog.version_vector(log, W))
    )
    assert tree_equal(compactlog.rebuild(c), oplog.rebuild(log, K))


def test_merge_equals_raw_union_across_frontier_chain():
    """merge over (behind, ahead) frontier pairs — dead-replica revival —
    equals the raw oplog union, observably."""
    rng = np.random.default_rng(4)
    for trial in range(10):
        ops = writer_histories(rng)
        a_log = prefix_log(ops, rand_prefixes(rng, ops))
        b_log = prefix_log(ops, rand_prefixes(rng, ops))
        want = oplog.rebuild(oplog.merge(a_log, b_log), K)

        # chain: f0 ≤ f1; a (revived) folded only f0, b reached f1
        f0 = _rand_stable_frontier(rng, a_log, b_log)
        f1 = _rand_stable_frontier(rng, b_log)
        f1 = jnp.maximum(f0, f1)
        a = compactlog.compact(compactlog.fresh(a_log, K, W), f0)
        b = compactlog.compact(
            compactlog.compact(compactlog.fresh(b_log, K, W), f0), f1
        )
        for m in (compactlog.merge(a, b), compactlog.merge(b, a)):
            assert tree_equal(compactlog.rebuild(m), want)
            assert np.array_equal(
                np.asarray(m.frontier), np.asarray(jnp.maximum(a.frontier, b.frontier))
            )


def test_merge_laws_same_frontier():
    """Within one frontier generation, merge is a lattice join: commutative,
    associative, idempotent (structurally — canonical sorted tails)."""
    rng = np.random.default_rng(5)
    ops = writer_histories(rng)
    logs = [prefix_log(ops, rand_prefixes(rng, ops)) for _ in range(3)]
    f = _rand_stable_frontier(rng, *logs)
    a, b, c = (
        compactlog.compact(compactlog.fresh(l, K, W), f) for l in logs
    )
    assert tree_equal(compactlog.merge(a, b), compactlog.merge(b, a))
    assert tree_equal(
        compactlog.merge(compactlog.merge(a, b), c),
        compactlog.merge(a, compactlog.merge(b, c)),
    )
    assert tree_equal(compactlog.merge(a, a), a)


# ---- swarm integration: gossip + compaction barriers + faults ----


def _compact_swarm(logs):
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[compactlog.fresh(l, K, W) for l in logs],
    )
    return swarm.make(stacked)


def test_swarm_compaction_round_bounds_tails():
    rng = np.random.default_rng(6)
    ops = writer_histories(rng, max_per_writer=10)
    logs = [prefix_log(ops, rand_prefixes(rng, ops)) for _ in range(6)]
    want_each = [oplog.rebuild(l, K) for l in logs]
    s = _compact_swarm(logs)

    s2 = swarm.compaction_round(
        s, compactlog.received_vv, compactlog.compact, lambda c: c.frontier
    )
    # every replica folded the same frontier; nothing observable changed
    fr = np.asarray(s2.state.frontier)
    assert (fr == fr[0]).all()
    for i, want in enumerate(want_each):
        got = compactlog.rebuild(jax.tree.map(lambda x, _i=i: x[_i], s2.state))
        assert tree_equal(got, want)
    # tails shrank by exactly the folded stable prefix
    before = np.asarray(jax.vmap(compactlog.size)(s.state))
    after = np.asarray(jax.vmap(compactlog.size)(s2.state))
    assert (after <= before).all()
    vvs = np.stack([np.asarray(oplog.version_vector(l, W)) for l in logs])
    assert (after == before - np.sum(vvs.min(axis=0) + 1)).all()


def test_swarm_gossip_then_compact_then_converge():
    """Full lifecycle: gossip rounds, a compaction barrier mid-flight, more
    gossip — every replica converges to the union's view with empty tails
    after a final barrier."""
    rng = np.random.default_rng(7)
    ops = writer_histories(rng, max_per_writer=10)
    logs = [prefix_log(ops, rand_prefixes(rng, ops)) for _ in range(6)]
    union = logs[0]
    for l in logs[1:]:
        union = oplog.merge(union, l)
    want = oplog.rebuild(union, K)

    s = _compact_swarm(logs)
    join_b = jax.vmap(compactlog.merge)
    key = jax.random.key(7)
    for i in range(12):
        key, k = jax.random.split(key)
        peers = swarm.random_peers(k, swarm.n_replicas(s))
        s = swarm.gossip_round(s, peers, join_b)
        if i == 3:
            s = swarm.compaction_round(
                s, compactlog.received_vv, compactlog.compact,
                lambda c: c.frontier,
            )
    neutral = compactlog.empty(CAP, K, W)
    s = swarm.converge(s, join_b, neutral)
    s = swarm.compaction_round(s, compactlog.received_vv, compactlog.compact, lambda c: c.frontier)
    for i in range(len(logs)):
        got = compactlog.rebuild(jax.tree.map(lambda x, _i=i: x[_i], s.state))
        assert tree_equal(got, want)
    # everything stable got folded: tails are empty
    assert (np.asarray(jax.vmap(compactlog.size)(s.state)) == 0).all()


def test_dead_replica_misses_barrier_then_catches_up():
    rng = np.random.default_rng(8)
    ops = writer_histories(rng, max_per_writer=10)
    logs = [prefix_log(ops, rand_prefixes(rng, ops)) for _ in range(4)]
    union = logs[0]
    for l in logs[1:]:
        union = oplog.merge(union, l)
    want = oplog.rebuild(union, K)

    s = _compact_swarm(logs)
    join_b = jax.vmap(compactlog.merge)
    neutral = compactlog.empty(CAP, K, W)
    dead = 2
    s = swarm.set_alive(s, dead, False)
    s = swarm.converge(s, join_b, neutral)               # alive-only fixpoint
    s = swarm.compaction_round(s, compactlog.received_vv, compactlog.compact, lambda c: c.frontier)
    # dead replica kept its state and its -1 frontier (behind on the chain)
    assert int(s.state.frontier[dead].max()) == -1

    s = swarm.set_alive(s, dead, True)
    s = swarm.converge(s, join_b, neutral)               # revival catch-up
    for i in range(len(logs)):
        got = compactlog.rebuild(jax.tree.map(lambda x, _i=i: x[_i], s.state))
        assert tree_equal(got, want)


def test_barrier_skipped_when_frontier_holders_dead():
    """Chain rule: a barrier held while the only holders of the previous
    frontier are dead must NOT advance (the alive set lacks ops that exist
    only inside the dead replicas' summaries); it resumes after revival."""
    rng = np.random.default_rng(9)
    ops = writer_histories(rng, max_per_writer=6)
    full = [int((ops["rid"] == w).sum()) for w in range(W)]
    # replicas 0,1 know writers 0,1 fully; replica 2 knows only writer 2
    know_01 = prefix_log(ops, [full[0], full[1], 0])
    know_2 = prefix_log(ops, [0, 0, full[2]])
    union = oplog.merge(know_01, know_2)
    want = oplog.rebuild(union, K)

    s = _compact_swarm([know_01, know_01, know_2])
    join_b = jax.vmap(compactlog.merge)
    neutral = compactlog.empty(CAP, K, W)
    args = (compactlog.received_vv, compactlog.compact, lambda c: c.frontier)

    # barrier 1: replica 2 dead -> 0,1 fold writers 0,1
    s = swarm.set_alive(s, 2, False)
    s = swarm.compaction_round(s, *args)
    f1 = np.asarray(s.state.frontier)
    assert (f1[0] == [full[0] - 1, full[1] - 1, -1]).all()

    # now 0,1 die and 2 revives: barrier must SKIP (frontiers unchanged)
    s = swarm.set_alive(s, 0, False)
    s = swarm.set_alive(s, 1, False)
    s = swarm.set_alive(s, 2, True)
    s2 = swarm.compaction_round(s, *args)
    assert np.array_equal(np.asarray(s2.state.frontier), f1)

    # full revival: converge spreads the fold, then the barrier resumes
    for r in range(3):
        s2 = swarm.set_alive(s2, r, True)
    s2 = swarm.converge(s2, join_b, neutral)
    s2 = swarm.compaction_round(s2, *args)
    fr = np.asarray(s2.state.frontier)
    assert (fr == [f - 1 for f in full]).all()
    for i in range(3):
        got = compactlog.rebuild(jax.tree.map(lambda x, _i=i: x[_i], s2.state))
        assert tree_equal(got, want)
