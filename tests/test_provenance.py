"""Convergence flight recorder: vv-delta visibility derivation,
exactly-once propagation accounting under duplicate/reordered delivery,
device-time attribution, and the offline assembler round-trip.

The exactly-once property under test is STRUCTURAL (crdt_tpu.obs
.provenance): visibility ranges are derived from the version-vector
delta of each merge, the vv is monotone per writer, so ranges of
successive rounds are disjoint and a delivery that teaches the node
nothing (duplicate, reorder) moves no vv and emits nothing.
"""
from __future__ import annotations

import json

import pytest

from crdt_tpu.api.net import NetworkAgent, NodeHost
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.faults import FaultPlane, FaultRule, FaultyTransport, NemesisSchedule
from crdt_tpu.obs import assemble
from crdt_tpu.obs.events import SCHEMA_VERSION, EventLog, read_jsonl
from crdt_tpu.obs.provenance import (
    BirthLedger,
    FlightRecorder,
    propagation_summary,
)
from crdt_tpu.obs.registry import NULL_REGISTRY, MetricsRegistry
from crdt_tpu.utils.clock import HostClock
from crdt_tpu.utils.config import ClusterConfig
from crdt_tpu.utils.metrics import Metrics


def _steps_count(registry, origin, node) -> int:
    h = registry.histogram("op_propagation_steps",
                           origin=str(origin), node=str(node))
    return h.count if h is not None else 0


def _instrumented(rid, step):
    """ReplicaNode with a real registry + installed ledger/step clock."""
    node = ReplicaNode(rid=rid, capacity=64,
                       metrics=Metrics(registry=MetricsRegistry()))
    ledger = BirthLedger()
    node.recorder.install(ledger=ledger, step_clock=lambda: step["n"])
    return node, ledger


# ----------------------------------------------------------- birth ledger


def test_birth_ledger_basics():
    led = BirthLedger()
    assert led.birth_step(0, 0) is None and len(led) == 0
    led.note(0, 0, 5)
    led.note(0, 1, 6)
    led.note(7, 0, 9)
    assert led.birth_step(0, 0) == 5
    assert led.birth_step(0, 1) == 6
    assert led.birth_step(7, 0) == 9
    assert led.birth_step(0, 2) is None
    assert len(led) == 3
    led.note(0, 0, 8)  # overwrite keeps lookups defined
    assert led.birth_step(0, 0) == 8
    led.note(3, 4, 2)  # hole: backfilled conservatively
    assert led.birth_step(3, 0) == 2 and led.birth_step(3, 4) == 2


# ------------------------------------------- vv-delta range derivation


def test_note_visible_derives_ranges_from_vv_delta():
    reg = MetricsRegistry()
    events = EventLog(node="9")
    rec = FlightRecorder(9, reg, events=events)
    led = BirthLedger()
    for seq, step in ((0, 0), (1, 1), (2, 4)):
        led.note(1, seq, step)
    rec.install(ledger=led, step_clock=lambda: 10)
    n = rec.note_visible({1: -1}, {1: 2})
    assert n == 3
    assert _steps_count(reg, 1, 9) == 3
    [ev] = events.find(event="op_visible")
    assert (ev["origin"], ev["seq_lo"], ev["seq_hi"], ev["n"]) == (1, 0, 2, 3)
    assert ev["lag_steps"] == 10  # oldest seq: born step 0, seen step 10
    # same vv again: no progress, nothing emitted (exactly-once)
    assert rec.note_visible({1: 2}, {1: 2}) == 0
    assert _steps_count(reg, 1, 9) == 3
    # regressed vv (reordered old payload): nothing
    assert rec.note_visible({1: 2}, {1: 1}) == 0
    # next disjoint range continues where the last stopped
    led.note(1, 3, 6)
    assert rec.note_visible({1: 2}, {1: 3}) == 1
    assert _steps_count(reg, 1, 9) == 4


def test_note_visible_skips_own_and_foreign_origins():
    reg = MetricsRegistry()
    rec = FlightRecorder(2, reg)
    # own writes (origin == rid) and watermarkless Go rows (origin < 0)
    # are not propagation
    assert rec.note_visible({}, {2: 5, -1: 3}) == 0
    assert reg.histograms("op_propagation_steps") == []


# ---------------------------------- node-level exactly-once (full stack)


def test_receive_duplicate_and_reorder_emit_once():
    step = {"n": 0}
    writer, _ = _instrumented(0, step)
    puller, ledger = _instrumented(1, step)
    puller.recorder.install(ledger=writer.recorder.ledger)  # share one
    for i in range(3):
        step["n"] = i
        assert writer.add_command({f"k{i}": str(i)})
    old = writer.gossip_payload()
    step["n"] = 5
    assert writer.add_command({"k3": "3"})
    new = writer.gossip_payload()

    step["n"] = 7
    assert puller.receive(new) > 0
    reg = puller.metrics.registry
    assert _steps_count(reg, 0, 1) == 4
    # byte-identical duplicate: vv unchanged -> zero new observations
    assert puller.receive(new) == 0
    assert _steps_count(reg, 0, 1) == 4
    # older payload after newer (the PR 4 redelivery-queue shape): nothing
    assert puller.receive(old) == 0
    assert _steps_count(reg, 0, 1) == 4
    # events agree: ranges are disjoint and cover each seq exactly once
    seen = []
    for ev in puller.events.find(event="op_visible"):
        seen.extend(range(ev["seq_lo"], ev["seq_hi"] + 1))
    assert sorted(seen) == [0, 1, 2, 3]


def test_receive_many_fused_counts_overlaps_once():
    step = {"n": 0}
    writer, ledger = _instrumented(0, step)
    puller, _ = _instrumented(1, step)
    puller.recorder.install(ledger=writer.recorder.ledger)
    assert writer.add_command({"a": "1"})
    p1 = writer.gossip_payload()
    assert writer.add_command({"b": "2"})
    p2 = writer.gossip_payload()  # superset of p1
    step["n"] = 3
    # one fused round carrying overlapping payloads: ONE vv delta, so the
    # shared seq is visible exactly once
    assert puller.receive_many([p1, p2]) == 2
    assert _steps_count(puller.metrics.registry, 0, 1) == 2


def test_redelivery_queue_duplicate_exactly_once():
    """Same property through the PR 4 fault plane: a 'duplicate' wire
    fault queues a byte-identical redelivery; the second delivery must
    observe nothing."""
    host = NodeHost(rid=1, peers=[], port=0)
    host.node.add_command({"x": "1"}, ts=10)
    host.node.add_command({"y": "2"}, ts=11)
    host.start_server()
    try:
        plane = FaultPlane(NemesisSchedule(
            seed=0, steps=1000, nodes=2,
            rules=(FaultRule("duplicate"),), skews=(),
        ))
        node = ReplicaNode(rid=0, capacity=64,
                           metrics=Metrics(registry=MetricsRegistry()))
        agent = NetworkAgent(node, [], ClusterConfig())
        t = FaultyTransport(host.url, plane, "0", "1")
        assert agent.pull_from(t)  # delivered AND queued for redelivery
        assert t.pending_redelivery() == 1
        assert _steps_count(node.metrics.registry, 1, 0) == 0  # no ledger
        h = node.metrics.registry.histogram("op_propagation",
                                            origin="1", node="0")
        assert h is not None and h.count == 2
        assert not agent.pull_from(t)  # the queued duplicate lands
        h2 = node.metrics.registry.histogram("op_propagation",
                                             origin="1", node="0")
        assert h2.count == 2  # exactly once per (origin, seq, observer)
    finally:
        host.stop_server()


def test_propagation_seconds_across_epochs():
    """The seconds histogram derives from the op's absolute WIRE ts, so
    it survives different host-clock epochs (cross-process shape)."""
    writer = ReplicaNode(rid=0, capacity=64, clock=HostClock(),
                         metrics=Metrics(registry=MetricsRegistry()))
    puller = ReplicaNode(rid=1, capacity=64, clock=HostClock(),
                         metrics=Metrics(registry=MetricsRegistry()))
    assert writer.clock.epoch_ms != 0 or True  # epochs are independent
    writer.add_command({"a": "1"})
    assert puller.receive(writer.gossip_payload()) > 0
    h = puller.metrics.registry.histogram("op_propagation",
                                          origin="0", node="1")
    assert h is not None and h.count == 1
    assert h.sum >= 0.0  # clamped: skew can't go negative


def test_propagation_summary_rolls_up_edges():
    step = {"n": 0}
    writer, _ = _instrumented(0, step)
    puller, _ = _instrumented(1, step)
    puller.recorder.install(ledger=writer.recorder.ledger)
    writer.add_command({"a": "1"})
    step["n"] = 2
    puller.receive(writer.gossip_payload())
    out = propagation_summary(writer.metrics.registry,
                              puller.metrics.registry)
    assert out["propagation_steps_count"] == 1
    assert out["propagation_s_count"] == 1
    assert out["propagation_steps_p50"] >= 2.0  # lag 2 -> bucket bound


def test_recorder_disabled_with_null_registry():
    node = ReplicaNode(rid=0, capacity=64,
                       metrics=Metrics(registry=NULL_REGISTRY))
    assert not node.recorder.enabled
    node.add_command({"a": "1"})
    assert node.events.find(event="op_birth") == []


# ------------------------------------------------- device-time attribution


def test_devtime_join_histogram_and_cost_gauges():
    from crdt_tpu.obs import devtime

    # the gauge sampler is per-(node, kind) across the process; reset so
    # this node's first dispatch is the one that lands the gauges
    devtime._dispatch_counts.pop(("0", "merge"), None)
    node = ReplicaNode(rid=0, capacity=64,
                       metrics=Metrics(registry=MetricsRegistry()))
    node.add_command({"a": "1"})
    reg = node.metrics.registry
    h = reg.histogram("join_device", node="0", kind="merge")
    assert h is not None and h.count == 1
    # the first dispatch always lands the sampled cost gauges (CPU
    # backend exposes a cost model; if it ever stops, the unavailable
    # counter must count it instead of silence)
    unavailable = reg.counter_value("join_cost_analysis_unavailable",
                                    node="0", kind="merge")
    nbytes = reg.gauge_value("join_bytes_per_dispatch",
                             node="0", kind="merge")
    assert (nbytes is not None and nbytes > 0) or unavailable == 1


def test_dispatch_annotation_carries_trace_id():
    from crdt_tpu.obs import devtime
    from crdt_tpu.obs.trace import span

    with span("crdt.pull") as tid:
        with devtime.dispatch_annotation("merge") as label:
            assert label == f"crdt.join.merge#trace={tid}"
    with devtime.dispatch_annotation("merge", enabled=False) as label:
        assert label is None


# ----------------------------------------------------- events satellites


def test_event_ring_eviction_is_counted():
    reg = MetricsRegistry()
    log = EventLog(node="3", capacity=4, registry=reg)
    for i in range(6):
        log.emit("tick", i=i)
    assert log.dropped == 2
    assert reg.counter_value("events_dropped", node="3") == 2
    assert len(log) == 4
    assert log.tail(1)[0]["i"] == 5  # newest survives, oldest evicted


def test_schema_version_and_step_stamped(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = EventLog(node="1", path=path, step_clock=lambda: 41)
    rec = log.emit("boot", port=1)
    assert rec["v"] == SCHEMA_VERSION == 2
    assert rec["step"] == 41
    log.close()
    [line] = read_jsonl(path)
    assert line["v"] == 2 and line["step"] == 41 and line["event"] == "boot"


def test_ring_dropped_gauge_in_health_sample():
    from crdt_tpu.obs import health

    node = ReplicaNode(rid=0, capacity=64,
                       metrics=Metrics(registry=MetricsRegistry()),
                       events=EventLog(node="0", capacity=2))
    for i in range(5):
        node.events.emit("tick", i=i)
    health.sample_kv_node(node.metrics.registry, node)
    assert node.metrics.registry.gauge_value(
        "events_ring_dropped", node="0") == 3


# ------------------------------------------------------------- assembler


def _write_jsonl(path, records):
    with open(path, "w", encoding="utf-8") as fh:
        for r in records:
            fh.write(json.dumps(r, sort_keys=True) + "\n")
    return str(path)


def _synthetic_logs(tmp_path, with_fault_window=False):
    """Two node logs for one gossip round (node 1 serves, node 0 pulls)
    plus births/visibilities; optionally a second, laggy visibility."""
    t = 1_000_000
    n1 = [
        {"v": 2, "ts_ms": t + 1, "node": "1", "event": "boot", "step": 0},
        {"v": 2, "ts_ms": t + 2, "node": "1", "event": "op_birth",
         "origin": 1, "seq": 0, "op_ts_ms": t + 2, "step": 0},
        {"v": 2, "ts_ms": t + 10, "node": "1", "event": "gossip_serve",
         "trace": "tr-1", "ops": 1, "step": 2},
    ]
    n0 = [
        {"v": 2, "ts_ms": t + 0, "node": "0", "event": "boot", "step": 0},
        {"v": 2, "ts_ms": t + 12, "node": "0", "event": "pull_merge",
         "trace": "tr-1", "fresh": 1, "step": 2},
        {"v": 2, "ts_ms": t + 12, "node": "0", "event": "op_visible",
         "trace": "tr-1", "origin": 1, "seq_lo": 0, "seq_hi": 0, "n": 1,
         "lag_steps": 2, "step": 2},
    ]
    if with_fault_window:
        # enough low-lag visibilities that the median stays at 2 and the
        # spike threshold sits at the floor (12) — then one 60-step lag
        for k in (1, 2):
            n1.append({"v": 2, "ts_ms": t + 13 + k, "node": "1",
                       "event": "op_birth", "origin": 1, "seq": k,
                       "op_ts_ms": t + 13 + k, "step": 2 + k})
            n0.append({"v": 2, "ts_ms": t + 16 + k, "node": "0",
                       "event": "op_visible", "origin": 1, "seq_lo": k,
                       "seq_hi": k, "n": 1, "lag_steps": 2, "step": 4 + k})
        n1.append({"v": 2, "ts_ms": t + 20, "node": "1",
                   "event": "op_birth", "origin": 1, "seq": 3,
                   "op_ts_ms": t + 20, "step": 5})
        n0.append({"v": 2, "ts_ms": t + 90, "node": "0",
                   "event": "op_visible", "origin": 1, "seq_lo": 3,
                   "seq_hi": 3, "n": 1, "lag_steps": 60, "step": 65})
    return (_write_jsonl(tmp_path / "node0.jsonl", n0),
            _write_jsonl(tmp_path / "node1.jsonl", n1))


def test_assembler_round_trip_two_nodes(tmp_path):
    p0, p1 = _synthetic_logs(tmp_path)
    records = assemble.load_node_logs([p0, p1])
    assert [r["node"] for r in records][0] == "0"  # ts-sorted
    trace = assemble.assemble_trace(records)
    evs = trace["traceEvents"]
    names = {e.get("args", {}).get("name") for e in evs if e["ph"] == "M"}
    assert {"node slot 0", "node slot 1",
            "nemesis (applied faults)"} <= names
    [x] = [e for e in evs if e["ph"] == "X"]
    assert x["name"] == "pull_merge" and x["args"]["trace"] == "tr-1"
    assert x["dur"] >= 1
    flows = [e for e in evs if e["ph"] in ("s", "f")]
    assert {e["ph"] for e in flows} == {"s", "f"}  # serve->merge link
    assert len({e["id"] for e in flows}) == 1
    # boots (never part of a span) appear as instants on their own track
    assert any(e["ph"] == "i" and e["name"] == "boot" for e in evs)
    blame = assemble.blame_report(records)
    assert blame["n_visible"] == 1
    assert blame["n_spikes"] == 0 and blame["coverage"] == 1.0


def test_blame_attributes_spike_to_fault_window(tmp_path):
    p0, p1 = _synthetic_logs(tmp_path, with_fault_window=True)
    records = assemble.load_node_logs([p0, p1])
    # without any fault evidence the spike must be flagged, not dropped
    blame = assemble.blame_report(records)
    assert blame["n_spikes"] == 1
    assert blame["spikes"][0]["cause"] == "unexplained"
    assert blame["coverage"] == 0.0
    # a partition window (drop records) covering birth->visible explains it
    faults = [{"step": 10, "fault": "drop", "src": "1", "dst": "0",
               "op": "gossip"}]
    blame = assemble.blame_report(records, faults)
    assert blame["n_spikes"] == 1 and blame["coverage"] == 1.0
    assert blame["spikes"][0]["cause"]["kind"] == "drop"
    # a fault on an UNINVOLVED edge does not explain this spike
    blame = assemble.blame_report(
        records, [{"step": 10, "fault": "drop", "src": "2", "dst": "3"}])
    assert blame["spikes"][0]["cause"] == "unexplained"


def test_assemble_cli_and_postmortem(tmp_path):
    p0, p1 = _synthetic_logs(tmp_path, with_fault_window=True)
    faults = _write_jsonl(
        tmp_path / "faults.jsonl",
        [{"step": 10, "fault": "drop", "src": "1", "dst": "0"}],
    )
    out = tmp_path / "trace.json"
    blame_out = tmp_path / "blame.json"
    rc = assemble.main([p0, p1, "--fault-log", faults, "--out", str(out),
                        "--blame", str(blame_out),
                        "--min-coverage", "0.95"])
    assert rc == 0
    trace = json.loads(out.read_text())
    assert trace["traceEvents"] and trace["displayTimeUnit"] == "ms"
    # the nemesis track carries the fault instant, placed via step anchors
    assert any(e["tid"] == 0 and e["ph"] == "i"
               for e in trace["traceEvents"])
    assert json.loads(blame_out.read_text())["coverage"] == 1.0
    # unexplained spike -> coverage gate fails loudly
    rc = assemble.main([p0, p1, "--out", str(out),
                        "--min-coverage", "0.95"])
    assert rc == 1
    # postmortem bundle carries logs + faults + trace + blame
    import tarfile

    bundle = assemble.write_postmortem(
        str(tmp_path / "pm" / "postmortem-0.tar.gz"), [p0, p1],
        fault_records=[{"step": 10, "fault": "drop"}])
    with tarfile.open(bundle) as tf:
        names = set(tf.getnames())
    assert {"node0.jsonl", "node1.jsonl", "faults.jsonl",
            "trace.json", "blame.json"} <= names


def test_obs_main_dispatches_assemble(tmp_path, capsys):
    from crdt_tpu.obs.__main__ import main as obs_main

    p0, p1 = _synthetic_logs(tmp_path)
    out = tmp_path / "t.json"
    assert obs_main(["assemble", p0, p1, "--out", str(out)]) == 0
    assert json.loads(out.read_text())["traceEvents"]
    assert obs_main(["no-such-cmd"]) == 2


# ---- keyspace tier: tenant/shard-labeled propagation (ISSUE 16) ----


def test_keyspace_tenant_labeled_exactly_once_dup_reorder():
    """Tenant writes through the front door propagate to a peer keyspace
    with {tenant, shard}-labeled exactly-once derivation: duplicate and
    stale-reordered payload deliveries add ZERO observations, and the
    op_visible events carry the shard label plus the per-tenant count."""
    from crdt_tpu.keyspace import KeyspaceFrontDoor, ShardedKeyspace

    step = {"n": 0}
    writer = ShardedKeyspace(rid=0, n_shards=2, capacity=64,
                             metrics=Metrics(registry=MetricsRegistry()))
    puller = ShardedKeyspace(rid=1, n_shards=2, capacity=64,
                             metrics=Metrics(registry=MetricsRegistry()))
    # per-shard FLEET-WIDE ledgers: shard i shares one (rid, seq) space
    # on every member, disjoint from its siblings'
    ledgers = [BirthLedger(), BirthLedger()]
    for ks in (writer, puller):
        for i, shard in enumerate(ks.shards):
            shard.recorder.install(ledger=ledgers[i],
                                   step_clock=lambda: step["n"])
    door = KeyspaceFrontDoor(writer, max_batch=1)
    for i in range(3):
        step["n"] = i
        assert door.admit_kv("t-acme", f"k{i}", str(i), timeout=5.0)
    old = [writer.gossip_payload(s, None) for s in range(2)]
    step["n"] = 4
    assert door.admit_kv("t-acme", "k3", "3", timeout=5.0)
    assert door.admit_kv("t-bolt", "kb", "vb", timeout=5.0)
    new = [writer.gossip_payload(s, None) for s in range(2)]

    def tenant_counts():
        out = {}
        reg = puller.shards[0].metrics.registry  # shared across shards
        for labels, h in reg.histograms("op_propagation_steps"):
            t = labels.get("tenant")
            if t:
                assert labels["shard"] in ("0", "1")
                assert labels["origin"] == "0" and labels["node"] == "1"
                out[t] = out.get(t, 0) + h.count
        return out

    step["n"] = 6
    assert sum(puller.receive(s, new[s]) for s in range(2)) == 5
    assert tenant_counts() == {"t-acme": 4, "t-bolt": 1}
    # byte-identical duplicates: vv unchanged -> zero new observations
    assert sum(puller.receive(s, new[s]) for s in range(2)) == 0
    # stale payloads after newer ones (reorder): still zero
    assert sum(puller.receive(s, old[s]) for s in range(2)) == 0
    assert tenant_counts() == {"t-acme": 4, "t-bolt": 1}
    # events agree: shard-labeled, each seq exactly once per shard, and
    # the tenants rollup matches the histogram counts
    seen = {}
    tenants = {}
    for shard in puller.shards:  # each shard keeps its own black box here
        for ev in shard.events.find(event="op_visible"):
            key = (ev["shard"], ev["origin"])
            seen.setdefault(key, []).extend(
                range(ev["seq_lo"], ev["seq_hi"] + 1))
            for t, n in (ev.get("tenants") or {}).items():
                tenants[t] = tenants.get(t, 0) + n
    for key, seqs in seen.items():
        assert sorted(seqs) == sorted(set(seqs)), key
    assert tenants == {"t-acme": 4, "t-bolt": 1}


def test_assembler_lease_track_round_trip(tmp_path):
    """Lease events assemble into the per-slot track: fence epochs as
    counter samples, lease instants on the slot track, and a handoff
    (grant by a DIFFERENT node) drawn as a flow arrow between the two
    holders' node tracks."""
    t = 1_000_000
    n0 = [
        {"v": 2, "ts_ms": t, "node": "0", "event": "boot", "step": 0},
        {"v": 2, "ts_ms": t + 5, "node": "0", "event": "lease_grant",
         "slot": 0, "fence": 1, "holder": "http://a", "trace": "tr-l1",
         "step": 1},
        {"v": 2, "ts_ms": t + 8, "node": "0", "event": "lease_renew",
         "slot": 0, "fence": 1, "holder": "http://a", "step": 2},
        {"v": 2, "ts_ms": t + 20, "node": "0", "event": "lease_expire",
         "slot": 0, "fence": 1, "step": 4},
    ]
    n1 = [
        {"v": 2, "ts_ms": t + 1, "node": "1", "event": "boot", "step": 0},
        {"v": 2, "ts_ms": t + 30, "node": "1", "event": "lease_grant",
         "slot": 0, "fence": 2, "holder": "http://b", "trace": "tr-l2",
         "step": 6},
        {"v": 2, "ts_ms": t + 35, "node": "1", "event":
         "cas_fenced_reject", "slot": 0, "fence": 1, "known": 2,
         "trace": "tr-z", "step": 7},
    ]
    p0 = _write_jsonl(tmp_path / "node0.jsonl", n0)
    p1 = _write_jsonl(tmp_path / "node1.jsonl", n1)
    records = assemble.load_node_logs([p0, p1])
    trace = assemble.assemble_trace(records)
    evs = trace["traceEvents"]
    # the slot track exists and is named
    meta = {e.get("args", {}).get("name") for e in evs if e["ph"] == "M"}
    assert "lease slot 0" in meta
    # fence epochs render as counter samples, monotone 1 -> 2
    fences = [e["args"]["fence"] for e in evs
              if e["ph"] == "C" and e["name"] == "lease fence s0"]
    assert fences == sorted(fences) and fences[-1] == 2
    # every lease event is an instant on the slot track
    kinds = [e["name"] for e in evs
             if e["ph"] == "i" and e.get("args", {}).get("slot") == 0]
    assert {"lease_grant", "lease_renew", "lease_expire",
            "cas_fenced_reject"} <= set(kinds)
    # the handoff (node 0's lease -> node 1's grant) is a flow arrow
    flows = [e for e in evs if e["ph"] in ("s", "f")
             and e["name"] == "lease_handoff"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert len({e["id"] for e in flows}) == 1
