"""Fleet SLO rollup tests (crdt_tpu.obs.fleet): the Prometheus text
round-trip the whole tier leans on, the per-tenant/per-shard/per-slot
summary fold, slo_breach accounting held 1:1 against shed provenance,
the CLI, and the live ``GET /fleet`` route.

The rollup's one invariant worth stating: the parse is EXACT — the
registry's log2 buckets are the exposition's buckets, so a parsed
histogram merges bit-identically with the one that rendered it.  Every
other number in the fleet view (quantiles, coverage, shed ratios) is
derived from that exactness, so the round-trip test anchors the file.
"""
from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from crdt_tpu.obs import fleet
from crdt_tpu.obs.events import EventLog
from crdt_tpu.obs.registry import MetricsRegistry

# ------------------------------------------------------- parser


def test_parse_prometheus_round_trip():
    reg = MetricsRegistry()
    reg.inc("ingest_shed", 3.0, lane="tenant", node="0", tenant="t-a")
    reg.inc("keyspace_tenant_ops", 7.0, tenant="t-a", node="0")
    reg.set_gauge("keyspace_shard_ops", 42.0, shard="1", node="0")
    # label escaping survives the round trip (quote, backslash, newline)
    reg.set_gauge("keyspace_tenant_depth", 2.0,
                  tenant='t-"q\\uo\nte"', node="0")
    for v in (0.001, 0.004, 0.25, 3.0, 3.0):
        reg.observe("ks_admit_latency", v, tenant="t-a", node="0")
    for lag in (0.0, 1.0, 2.0, 2.0, 40.0):
        reg.observe("op_propagation_steps", lag,
                    origin="1", node="0", tenant="t-a", shard="0")

    snap = fleet.parse_prometheus(reg.render_prometheus())

    assert snap.counters_named("ingest_shed") == [
        ({"lane": "tenant", "node": "0", "tenant": "t-a"}, 3.0)]
    assert snap.counters_named("keyspace_tenant_ops")[0][1] == 7.0
    assert snap.gauges_named("keyspace_shard_ops") == [
        ({"shard": "1", "node": "0"}, 42.0)]
    [(lbl, _)] = snap.gauges_named("keyspace_tenant_depth")
    assert lbl["tenant"] == 't-"q\\uo\nte"'
    # histograms rebuild EXACTLY: buckets, sum, count
    [(lbl, h)] = snap.hists_named("ks_admit_latency")
    src = reg.histogram("ks_admit_latency", tenant="t-a", node="0")
    assert lbl == {"tenant": "t-a", "node": "0"}
    assert h.buckets == src.buckets and h.count == src.count
    assert h.sum == pytest.approx(src.sum)
    assert h.quantile(0.5) == src.quantile(0.5)
    [(lbl, h)] = snap.hists_named("op_propagation_steps")
    src = reg.histogram("op_propagation_steps", origin="1", node="0",
                        tenant="t-a", shard="0")
    assert h.buckets == src.buckets and h.count == src.count


# ------------------------------------------------------- summary fold


def _two_member_texts(*, observed=4):
    """Member '0' admits 4 ops for t-a (and sheds 2 for t-noisy);
    member '1' observes ``observed`` of them propagate."""
    r0 = MetricsRegistry()
    r0.inc("keyspace_tenant_ops", 4.0, tenant="t-a", node="0")
    r0.inc("ingest_shed", 2.0, lane="tenant", node="0", tenant="t-noisy")
    r0.inc("ingest_shed_ops", 6.0, lane="tenant", node="0",
           tenant="t-noisy")
    r0.set_gauge("keyspace_tenant_quota", 8.0, tenant="t-noisy", node="0")
    r0.set_gauge("keyspace_shard_ops", 3.0, shard="0", node="0")
    r0.set_gauge("keyspace_shard_ops", 1.0, shard="1", node="0")
    r0.set_gauge("lease_state", 1.0, slot="0", node="0")
    r0.set_gauge("lease_fence_epoch", 3.0, slot="0", node="0")
    for v in (0.001, 0.002, 0.004, 0.008):
        r0.observe("ks_admit_latency", v, tenant="t-a", node="0")
    r1 = MetricsRegistry()
    for _ in range(observed):
        r1.observe("op_propagation_steps", 2.0,
                   origin="0", node="1", tenant="t-a", shard="0")
    r1.set_gauge("lease_state", 0.0, slot="0", node="1")
    r1.set_gauge("lease_fence_epoch", 3.0, slot="0", node="1")
    return {"0": r0.render_prometheus(), "1": r1.render_prometheus()}


def test_fleet_summary_tenant_shard_slot_rows():
    report = fleet.fleet_from_texts(_two_member_texts())
    assert report["n_members"] == 2 and report["members"] == ["0", "1"]

    ta = report["tenants"]["t-a"]
    assert ta["ops"] == 4 and ta["sheds"] == 0
    assert ta["admit_p99_ms"] is not None
    assert ta["prop_p99_steps"] is not None
    # exactly-once accounting: 4 admitted x (2 members - 1) = 4 expected,
    # 4 observed -> full coverage
    assert ta["prop_expected"] == 4 and ta["prop_observed"] == 4
    assert ta["prop_coverage"] == 1.0

    noisy = report["tenants"]["t-noisy"]
    assert noisy["sheds"] == 2 and noisy["shed_ops"] == 6
    assert noisy["quota"] == 8.0
    assert noisy["shed_ratio"] == 1.0  # 6 shed / (0 admitted + 6 shed)

    assert report["shards"]["0"]["ops_total"] == 3.0
    assert report["shard_balance"] == pytest.approx(1.5)  # 3 / mean(3,1)

    slot = report["slots"]["0"]
    assert slot["holder"] == "0" and slot["fence"] == 3
    assert slot["expired"] == []

    # default SLO: the all-shed tenant breaches shed_ratio, nothing else
    kinds = {(b["kind"], b["tenant"]) for b in report["slo_breaches"]}
    assert kinds == {("shed_ratio", "t-noisy")}


def test_partial_coverage_is_reported_not_clamped():
    report = fleet.fleet_from_texts(_two_member_texts(observed=3))
    assert report["tenants"]["t-a"]["prop_coverage"] == 0.75


def test_fleet_audit_rollup_worst_state_and_per_plane_rows():
    """The divergence-audit rollup (crdt_tpu.obs.audit): per-member
    watchdog states fold to the WORST as the one-number fleet verdict,
    per-plane agreement splits members into agree/disagree, and the
    divergence/scrub-drift counters sum fleet-wide."""
    r0 = MetricsRegistry()
    r0.set_gauge("audit_state", 1.0)
    r0.set_gauge("audit_agreement", 1.0, plane="host")
    r1 = MetricsRegistry()
    r1.set_gauge("audit_state", 2.0)
    r1.set_gauge("audit_agreement", 0.0, plane="host")
    r1.set_gauge("audit_agreement", 1.0, plane="ks-0")
    r1.inc("audit_divergences", 3.0)
    r1.inc("audit_scrub_drifts", 1.0)
    report = fleet.fleet_from_texts(
        {"0": r0.render_prometheus(), "1": r1.render_prometheus()})
    a = report["audit"]
    assert a["state"] == 2  # worst member latches the fleet verdict
    assert a["states"] == {"0": 1, "1": 2}
    assert a["planes"]["host"] == {"agree": ["0"], "disagree": ["1"]}
    assert a["planes"]["ks-0"] == {"agree": ["1"], "disagree": []}
    assert a["divergences"] == 3 and a["scrub_drifts"] == 1

    # members without the audit plane contribute nothing — not a verdict
    clean = fleet.fleet_from_texts({"0": MetricsRegistry()
                                    .render_prometheus()})
    assert clean["audit"]["state"] == 0 and clean["audit"]["states"] == {}


# ------------------------------------------------------- SLO + reconcile


def test_evaluate_slo_emits_events_and_reconciles():
    events = EventLog(node="0")
    report = fleet.fleet_from_texts(
        _two_member_texts(), slo={"shed_ratio": 0.5}, events=events)
    [breach] = [b for b in report["slo_breaches"]
                if b["kind"] == "shed_ratio"]
    assert breach["tenant"] == "t-noisy" and breach["n_sheds"] == 2
    assert breach["quota"] == 8.0
    recorded = list(events.find(event="slo_breach"))
    assert len(recorded) == len(report["slo_breaches"])
    assert any(e["tenant"] == "t-noisy" for e in recorded)

    # the breach's n_sheds must equal the ingest_shed provenance count —
    # same call site increments the counter and emits the event, so any
    # drift is a lost record
    shed_events = [{"event": "ingest_shed", "tenant": "t-noisy"}] * 2
    rec = fleet.reconcile_sheds(report["slo_breaches"], shed_events)
    assert rec["ok"] and rec["tenants"]["t-noisy"] == {
        "slo": 2, "provenance": 2, "ok": True}
    rec = fleet.reconcile_sheds(report["slo_breaches"], shed_events[:1])
    assert not rec["ok"] and not rec["tenants"]["t-noisy"]["ok"]


def test_lease_timeline_orders_and_filters():
    records = [
        {"event": "lease_renew", "slot": 0, "fence": 1, "node": "0",
         "ts_ms": 30},
        {"event": "pull_merge", "node": "1", "ts_ms": 5},  # not lease
        {"event": "lease_grant", "slot": 0, "fence": 1, "node": "0",
         "ts_ms": 10, "holder": "http://a"},
        {"event": "cas_fenced_reject", "slot": 0, "fence": 1, "known": 2,
         "node": "1", "ts_ms": 40, "trace": "tr-9"},
        {"event": "lease_grant", "slot": 1, "fence": 5, "node": "1",
         "ts_ms": 20},
    ]
    tl = fleet.lease_timeline(records)
    assert [r["event"] for r in tl["0"]] == [
        "lease_grant", "lease_renew", "cas_fenced_reject"]
    assert tl["0"][0]["holder"] == "http://a"
    assert tl["0"][2]["known"] == 2 and tl["0"][2]["trace"] == "tr-9"
    assert [r["fence"] for r in tl["1"]] == [5]


# ------------------------------------------------------- CLI


def test_fleet_cli_files_logs_and_coverage_gate(tmp_path, capsys):
    texts = _two_member_texts()
    paths = []
    for name, text in texts.items():
        p = tmp_path / f"member{name}.prom"
        p.write_text(text)
        paths.append(str(p))
    log = tmp_path / "events.jsonl"
    with open(log, "w") as fh:
        for _ in range(2):
            fh.write(json.dumps({"event": "ingest_shed",
                                 "tenant": "t-noisy", "node": "0"}) + "\n")
        fh.write(json.dumps({"event": "lease_grant", "slot": 0,
                             "fence": 3, "node": "0", "ts_ms": 1}) + "\n")
    out = tmp_path / "fleet.json"
    rc = fleet.main(paths + ["--logs", str(log), "--min-coverage", "95",
                             "--out", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["tenants"]["t-a"]["prop_coverage"] == 1.0
    assert report["shed_reconciliation"]["ok"]
    assert report["lease_timelines"]["0"][0]["event"] == "lease_grant"
    capsys.readouterr()

    # coverage shortfall fails the gate (one observation lost)
    short = tmp_path / "short.prom"
    texts = _two_member_texts(observed=3)
    short.write_text(texts["1"])
    (tmp_path / "m0.prom").write_text(texts["0"])
    rc = fleet.main([str(tmp_path / "m0.prom"), str(short),
                     "--min-coverage", "95"])
    assert rc == 1
    assert "coverage" in capsys.readouterr().err


# ------------------------------------------------------- GET /fleet


def test_fleet_http_route_end_to_end():
    """Two live NodeHosts with the keyspace tier: tenant writes + one
    forced quota shed on node a, then ``GET /fleet`` on a folds BOTH
    members' expositions, reports the tenant rows, flags the shed-ratio
    breach, and records slo_breach in a's black box."""
    import urllib.error

    from crdt_tpu.api.net import NodeHost, RemotePeer
    from crdt_tpu.keyspace import TENANT_HEADER
    from crdt_tpu.utils.config import ClusterConfig

    cfg = ClusterConfig(keyspace_shards=2, keyspace_capacity=64,
                        keyspace_tenant_quota={"t-noisy": 2})
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[], config=cfg)
    a.agent.peers = [RemotePeer(b.url)]
    for h in (a, b):
        threading.Thread(target=h._server.serve_forever,
                         daemon=True).start()
    try:
        def post(url, body, tenant):
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(), method="POST")
            req.add_header(TENANT_HEADER, tenant)
            return urllib.request.urlopen(req, timeout=5)

        assert post(a.url + "/data", {"k1": "v1", "k2": "v2"},
                    "t-acme").status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(a.url + "/data", {f"k{i}": "v" for i in range(3)},
                 "t-noisy")
        assert ei.value.code == 429

        body = urllib.request.urlopen(
            a.url + "/fleet?shed_ratio=0.001", timeout=5).read()
        report = json.loads(body)
        assert report["n_members"] == 2
        assert report["tenants"]["t-acme"]["ops"] == 2
        noisy = report["tenants"]["t-noisy"]
        assert noisy["sheds"] >= 1 and noisy["quota"] == 2.0
        assert any(b["kind"] == "shed_ratio" and b["tenant"] == "t-noisy"
                   for b in report["slo_breaches"])
        # shard balance section exists once the tier has traffic
        assert report["shards"] and report["shard_balance"] is not None
        # the rollup recorded its threshold crossings as events
        assert any(e["tenant"] == "t-noisy"
                   for e in a.node.events.find(event="slo_breach"))
        # bad query param is a 400, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(a.url + "/fleet?shed_ratio=nope",
                                   timeout=5)
        assert ei.value.code == 400
    finally:
        for h in (a, b):
            h._server.shutdown()
            h._server.server_close()
