"""Property tests of the join laws every CRDT lattice must satisfy:
commutativity, associativity, idempotence, and identity (SURVEY.md §4's
mandate — the reference has no tests at all; convergence there was eyeballed
via GET /data polling, /root/reference/main.go:273-314).

The ACI sweep parametrizes over ``registered_joins()`` — every join the
package exports, leaves AND algebra-derived composites, is law-checked on
randomized reachable states drawn by its own ``JoinSpec.rand`` generator.
Registering a join without ``rand``/``neutral`` fails here loudly, which
is the point: the registry is the single source of truth."""
import zlib

import numpy as np
import pytest

from crdt_tpu.models import gcounter, lww, oplog, orset, pncounter
from crdt_tpu.ops import joins
from tests import helpers
from tests.helpers import tree_equal

N_TRIALS = 20
# the registry sweep covers ~18 lattices x 7 joins per trial; a lighter
# trial count keeps tier-1 wall clock flat while every join still sees
# dozens of randomized states
N_REGISTRY_TRIALS = 12


def _registered_names():
    return sorted(joins.registered_joins())


@pytest.mark.parametrize("name", _registered_names())
def test_registered_join_laws(name):
    """ACI + identity on every registered join — the runtime half of the
    static gate (crdtlint CRDT101-104), driven entirely from the registry:
    states come from ``spec.rand``, the identity from ``spec.neutral``."""
    spec = joins.registered_joins()[name]
    assert spec.rand is not None, f"{name} registered no rand generator"
    assert spec.neutral is not None, f"{name} registered no neutral"
    join = spec.join
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    for _ in range(N_REGISTRY_TRIALS):
        a, b, c = spec.rand(rng), spec.rand(rng), spec.rand(rng)
        assert tree_equal(join(a, b), join(b, a)), "commutativity"
        assert tree_equal(join(join(a, b), c), join(a, join(b, c))), "associativity"
        assert tree_equal(join(a, a), a), "idempotence"
        assert tree_equal(join(a, spec.neutral()), a), "identity"


def test_registry_driven_converge():
    """converge()/tree_reduce_join accept a registered name: batching and
    the neutral pad element both come from the registry."""
    rng = np.random.default_rng(5)
    spec = joins.registered_joins()["pncounter"]
    states = [spec.rand(rng) for _ in range(5)]
    stacked = pncounter.PNCounter(
        pos=np.stack([np.asarray(s.pos) for s in states]),
        neg=np.stack([np.asarray(s.neg) for s in states]),
    )
    by_name = joins.converge("pncounter", stacked)
    by_spec = joins.converge(spec, stacked)
    explicit = joins.converge(
        joins.batched(pncounter.join), stacked, pncounter.zero(8))
    assert tree_equal(by_name, by_spec)
    assert tree_equal(by_name, explicit)
    # the bare-callable convention still requires an explicit neutral
    with pytest.raises(ValueError):
        joins.tree_reduce_join(joins.batched(pncounter.join), stacked)
    with pytest.raises(KeyError):
        joins.tree_reduce_join("no_such_join", stacked)


def test_oplog_join_laws():
    rng = np.random.default_rng(7)
    for _ in range(N_TRIALS):
        a, b, c = helpers.rand_oplog_family(rng, n_logs=3)
        j = oplog.merge
        assert tree_equal(j(a, b), j(b, a)), "commutativity"
        assert tree_equal(j(j(a, b), c), j(a, j(b, c))), "associativity"
        assert tree_equal(j(a, a), a), "idempotence"
        assert tree_equal(j(a, oplog.empty(a.capacity)), a), "identity"


def test_gcounter_value_and_increment():
    c = gcounter.zero(4)
    c = gcounter.increment(c, 1, 5)
    c = gcounter.increment(c, 3, 2)
    assert int(gcounter.value(c)) == 7


def test_pncounter_signed_deltas():
    # The reference workload only produces negative deltas (main.go:275-282);
    # make sure the negative plane carries them.
    c = pncounter.zero(4)
    for node, delta in [(0, -11), (1, -20), (0, 4)]:
        c = pncounter.add(c, node, delta)
    assert int(pncounter.value(c)) == -27


def test_lww_resolution_is_order_free():
    rng = np.random.default_rng(3)
    writes = [
        (int(rng.integers(0, 100)), int(rng.integers(0, 8)), i)
        for i in range(10)
    ]
    expected = max(writes)[2]
    reg = lww.zero()
    for ts, rid, payload in reversed(writes):
        reg = lww.write(reg, ts, rid, payload)
    assert int(lww.value(reg)) == expected


def test_lww_packed_roundtrip_and_equivalence():
    """The packed fast path (key = ts << rid_bits | rid+1) must be an exact
    order-preserving encoding: pack/unpack roundtrips bit-for-bit (incl.
    negative ts and the unset sentinel), and unpack(join_packed(pack a,
    pack b)) == join(a, b) — including exact (ts, rid) ties, where both
    paths keep the left operand."""
    rng = np.random.default_rng(11)
    for _ in range(N_TRIALS):
        a, b = helpers.rand_lww(rng, (64,)), helpers.rand_lww(rng, (64,))
        assert tree_equal(lww.unpack(lww.pack(a)), a)
        got = lww.unpack(lww.join_packed(lww.pack(a), lww.pack(b)))
        assert tree_equal(got, lww.join(a, b))
    # sentinel roundtrip + identity
    z = lww.zero((4,))
    assert tree_equal(lww.unpack(lww.pack(z)), z)
    a = helpers.rand_lww(rng, (4,))
    assert tree_equal(
        lww.unpack(lww.join_packed(lww.pack(a), lww.pack(z))), a)
    # exact (ts, rid) tie with different payloads: both paths keep LEFT
    t = lww.LWWRegister(ts=np.int32([5]), rid=np.int32([2]),
                        payload=np.int32([7]))
    u = t.replace(payload=np.int32([9]))
    assert int(lww.join(t, u).payload[0]) == 7
    assert int(lww.unpack(lww.join_packed(lww.pack(t), lww.pack(u))).payload[0]) == 7


def test_lww_packed_join_laws():
    rng = np.random.default_rng(13)
    for _ in range(N_TRIALS):
        a, b, c = (lww.pack(helpers.rand_lww(rng)) for _ in range(3))
        assert tree_equal(lww.join_packed(a, b), lww.join_packed(b, a))
        assert tree_equal(lww.join_packed(lww.join_packed(a, b), c),
                          lww.join_packed(a, lww.join_packed(b, c)))
        assert tree_equal(lww.join_packed(a, a), a)
        assert tree_equal(lww.join_packed(a, lww.pack(lww.zero())), a)


def test_lww_pack_budget():
    ok = helpers.rand_lww(np.random.default_rng(17), (8,))
    assert bool(lww.pack_budget_ok(ok))
    big_ts = ok.replace(ts=np.full(8, 1 << 28, np.int32))
    assert not bool(lww.pack_budget_ok(big_ts))  # overflows ts << 6
    big_rid = ok.replace(rid=np.full(8, 63, np.int32))
    assert not bool(lww.pack_budget_ok(big_rid))  # rid+1 needs 7 bits
    assert bool(lww.pack_budget_ok(big_rid, rid_bits=7))
    neg_rid = ok.replace(rid=np.full(8, -2, np.int32))
    assert not bool(lww.pack_budget_ok(neg_rid))


def test_orset_add_remove_readd():
    s = orset.empty(16)
    s = orset.add(s, elem=3, rid=0, seq=0)
    assert bool(orset.contains(s, 3))
    s = orset.remove(s, 3)
    assert not bool(orset.contains(s, 3))
    s = orset.add(s, elem=3, rid=1, seq=0)  # re-add with a fresh tag survives
    assert bool(orset.contains(s, 3))


def test_orset_observed_remove_concurrent_add_wins():
    # replica A adds, B observes and removes, meanwhile A adds again with a
    # new tag: the re-add must survive the join with B's tombstones.
    a = orset.empty(16)
    a = orset.add(a, elem=1, rid=0, seq=0)
    b = orset.join(orset.empty(16), a)  # B observes
    b = orset.remove(b, 1)
    a = orset.add(a, elem=1, rid=0, seq=1)  # concurrent re-add
    merged = orset.join(a, b)
    assert bool(orset.contains(merged, 1))
    assert list(np.asarray(orset.member_mask(merged, 4))) == [False, True, False, False]
