"""Cross-daemon compaction barrier tests (crdt_tpu.api.net.network_compact):
the distributed version of the LocalCluster barrier — version vectors
collected over HTTP, the swarm-stable frontier POSTed back, misses healed
by gossip frontier adoption.  (The reference never prunes at all:
/root/reference/main.go:75 clears only its staging buffer.)"""
from __future__ import annotations

import threading

import pytest

from crdt_tpu.api.net import NodeHost, RemotePeer, network_compact
from crdt_tpu.models import oplog


@pytest.fixture
def trio():
    """Three served NodeHosts, fully peered, agents driven manually."""
    hosts = [NodeHost(rid=r, peers=[]) for r in range(3)]
    for h in hosts:
        h.agent.peers = [RemotePeer(o.url) for o in hosts if o is not h]
        threading.Thread(target=h._server.serve_forever, daemon=True).start()
    yield hosts
    for h in hosts:
        h._server.shutdown()
        h._server.server_close()


def _converge(hosts, rounds=8):
    for _ in range(rounds):
        for h in hosts:
            h.agent.gossip_once()


def test_vv_endpoint(trio):
    a = trio[0]
    RemotePeer(a.url).add_command({"x": "1"})
    vv, frontier = RemotePeer(a.url).version_vector()
    assert vv == {0: 0} and frontier == {}
    a.node.set_alive(False)
    assert RemotePeer(a.url).version_vector() is None


def test_network_barrier_folds_everyone(trio):
    a, b, c = trio
    RemotePeer(a.url).add_command({"x": "5"})
    RemotePeer(b.url).add_command({"x": "2"})
    RemotePeer(c.url).add_command({"y": "hi"})
    _converge(trio)
    states = [h.node.get_state() for h in trio]
    assert states[0] == states[1] == states[2] == {"x": "7", "y": "hi"}

    frontier = network_compact(a.node, a.agent.peers)
    assert frontier == {0: 0, 1: 0, 2: 0}
    for h in trio:
        assert int(oplog.size(h.node.log)) == 0  # fully folded
        assert h.node._commands == {}
        assert h.node.get_state() == {"x": "7", "y": "hi"}  # unchanged
    # writes keep flowing after the fold
    RemotePeer(b.url).add_command({"x": "1"})
    _converge(trio)
    assert all(h.node.get_state()["x"] == "8" for h in trio)


def test_barrier_skipped_when_member_unreachable(trio):
    a, b, c = trio
    RemotePeer(a.url).add_command({"x": "5"})
    _converge(trio)
    c.node.set_alive(False)  # /vv now 502s
    assert network_compact(a.node, a.agent.peers) == {}
    for h in trio:
        assert h.node.frontier == {}  # nobody folded


def test_missed_compact_post_heals_via_gossip(trio):
    """A member whose POST /compact is lost (crash/drop between the vv
    collection and the fold) adopts the frontier+summary from any folded
    peer's gossip payload."""
    a, b, c = trio
    RemotePeer(a.url).add_command({"x": "5"})
    RemotePeer(b.url).add_command({"y": "2"})
    _converge(trio)
    # the coordinator computed the barrier over ALL members (everyone
    # converged, so every vv agrees), but c's POST got lost: only a and b
    # fold now
    frontier = {0: 0, 1: 0}
    a.node.compact(frontier)
    assert RemotePeer(b.url).compact(frontier)
    assert c.node.frontier == {}
    # c still holds every raw op, so delta gossip rightly ships it nothing
    # (its vv covers the peers' frontier — no sections needed); its state
    # stays correct and the NEXT barrier folds it too
    for _ in range(4):
        c.agent.gossip_once()
    assert c.node.get_state() == a.node.get_state()
    RemotePeer(c.url).add_command({"z": "9"})
    _converge(trio)
    frontier2 = network_compact(a.node, a.agent.peers)
    assert frontier2 == {0: 0, 1: 0, 2: 0}
    assert c.node.frontier == frontier2
    assert all(h.node.get_state() == a.node.get_state() for h in trio)

    # the sections DO ship to a requester that actually lacks ops: a fresh
    # member joining after the fold reconstructs full state from them
    fresh = NodeHost(rid=9, peers=[a.url])
    threading.Thread(target=fresh._server.serve_forever, daemon=True).start()
    try:
        fresh.agent.peers = [RemotePeer(a.url)]
        assert fresh.agent.gossip_once()
        assert fresh.node.frontier == frontier2
        assert fresh.node.get_state() == a.node.get_state()
    finally:
        fresh._server.shutdown()
        fresh._server.server_close()


def test_coordinator_loop_compacts(trio):
    a, b, c = trio
    for h in trio:
        h.config.gossip_period_ms = 30
        h.agent.config.gossip_period_ms = 30
    a.agent.config.compact_every = 3
    a.agent.coordinator = True
    RemotePeer(a.url).add_command({"x": "5"})
    RemotePeer(b.url).add_command({"x": "-2"})
    for h in trio:
        h.agent.start()
    try:
        import time

        deadline = time.time() + 15
        while time.time() < deadline:
            if all(h.node.frontier for h in trio) and all(
                h.node.get_state() == {"x": "3"} for h in trio
            ):
                break
            time.sleep(0.05)
        assert all(h.node.get_state() == {"x": "3"} for h in trio)
        assert all(h.node.frontier for h in trio)
    finally:
        for h in trio:
            h.agent.stop()