"""crdtlint self-tests: every rule fires on a minimal fixture exactly
once, guards suppress where documented, and the committed baseline is
clean against the current tree (the same invariant the CI gate enforces).
"""
import textwrap

import pytest

from crdt_tpu import analysis
from crdt_tpu.analysis import ast_checks, baseline, concurrency
from crdt_tpu.analysis import Finding


def _lint_snippet(tmp_path, source, relpath="fixture.py"):
    """Write ``source`` under tmp_path at ``relpath`` and AST-lint it
    (relpath controls the hot-package gating of CRDT003)."""
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return ast_checks.check_file(p, tmp_path)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------- CRDT001

def test_donation_after_use_fires_once(tmp_path):
    findings = _lint_snippet(tmp_path, """
        from crdt_tpu.ops import joins

        def round(a, b):
            merge = joins.donating(join)
            out = merge(a, b)
            return out + a
    """)
    assert _rules(findings) == ["CRDT001"]
    (f,) = findings
    assert "`a` was donated" in f.message
    assert f.severity == "error"


def test_donation_rebinding_resets(tmp_path):
    findings = _lint_snippet(tmp_path, """
        from crdt_tpu.ops import joins

        def round(a, b):
            merge = joins.donating(join)
            a = merge(a, b)
            return a
    """)
    assert findings == []


def test_jit_donate_argnums_tracked(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax

        def round(a, b):
            f = jax.jit(step, donate_argnums=(1,))
            out = f(a, b)
            return out + b
    """)
    assert _rules(findings) == ["CRDT001"]


# ---------------------------------------------------------------- CRDT002

def test_jit_in_loop_fires_once(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax

        def rounds(xs):
            outs = []
            for x in xs:
                f = jax.jit(step)
                outs.append(f(x))
            return outs
    """)
    assert _rules(findings) == ["CRDT002"]


def test_jit_hoisted_is_clean(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax

        def rounds(xs):
            f = jax.jit(step)
            return [f(x) for x in xs]
    """)
    assert findings == []


# ---------------------------------------------------------------- CRDT003

def test_host_sync_fires_in_hot_package(tmp_path):
    src = """
        import numpy as np

        def peek(x):
            return np.asarray(x)
    """
    hot = _lint_snippet(tmp_path, src, relpath="crdt_tpu/ops/fixture.py")
    assert _rules(hot) == ["CRDT003"]
    cold = _lint_snippet(tmp_path, src, relpath="crdt_tpu/harness/fixture.py")
    assert cold == []


# ---------------------------------------------------------------- CRDT004

def test_silent_except_fires_once(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def poll(url):
            try:
                fetch(url)
            except Exception:
                pass
    """)
    assert _rules(findings) == ["CRDT004"]
    assert findings[0].severity == "error"


def test_handled_except_is_clean(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def poll(url, events):
            try:
                fetch(url)
            except Exception as e:
                events.emit("poll_failed", error=str(e))
    """)
    assert findings == []


# ---------------------------------------------------------------- CRDT201

def test_unlocked_thread_mutation_fires_once(tmp_path):
    p = tmp_path / "agent.py"
    p.write_text(textwrap.dedent("""
        import threading

        class Agent:
            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.errors.append("boom")
    """))
    findings = concurrency.check_files([p], tmp_path)
    assert _rules(findings) == ["CRDT201"]
    assert "self.errors.append()" in findings[0].message


def test_locked_thread_mutation_is_clean(tmp_path):
    p = tmp_path / "agent.py"
    p.write_text(textwrap.dedent("""
        import threading

        class Agent:
            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self.errors.append("boom")
    """))
    assert concurrency.check_files([p], tmp_path) == []


# ------------------------------------------------------------- jaxpr layer

def _bad_registry():
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops.joins import JoinSpec

    def example():
        return jnp.zeros((4,), jnp.int32), jnp.zeros((4,), jnp.int32)

    def impure(a, b):
        out = jax.pure_callback(
            lambda x: x, jax.ShapeDtypeStruct(a.shape, a.dtype),
            jnp.maximum(a, b))
        return out

    def not_closed(a, b):
        return jnp.concatenate([a, b])

    def asymmetric(a, b):
        return a  # trivially non-symmetric under operand swap

    def select_max(a, b):
        return jnp.where(a > b, a, b)  # extensionally max, asymmetric jaxpr

    def symmetric(a, b):
        return jnp.maximum(a, b)

    return {
        "impure": JoinSpec("impure", impure, example),
        "not_closed": JoinSpec("not_closed", not_closed, example),
        "asymmetric": JoinSpec("asymmetric", asymmetric, example,
                               structurally_commutative=True),
        # an honestly-registered select join (claims False): clean ...
        "select_leaf": JoinSpec("select_leaf", select_max, example),
        # ... but a composite claiming commutativity OVER it must flag
        # CRDT104 even though its own jaxpr (pure maximum) passes CRDT103
        "bad_composite": JoinSpec("bad_composite", symmetric, example,
                                  structurally_commutative=True,
                                  parts=("select_leaf", "select_leaf")),
    }


def test_jaxpr_checks_catch_planted_defects(monkeypatch):
    from crdt_tpu.analysis import jaxpr_checks
    from crdt_tpu.ops import joins as joins_mod

    monkeypatch.setattr(joins_mod, "registered_joins", _bad_registry)
    findings = jaxpr_checks.check_registered_joins(analysis.repo_root())
    by_scope = {f.scope: f.rule for f in findings}
    assert by_scope == {
        "impure": "CRDT101",
        "not_closed": "CRDT102",
        "asymmetric": "CRDT103",
        "bad_composite": "CRDT104",
    }


def test_real_registry_is_clean_and_complete():
    """The acceptance invariant: every join the package exports traces
    callback-free, aval-closed, and swap-symmetric where claimed."""
    from crdt_tpu.analysis import jaxpr_checks
    from crdt_tpu.ops import joins as joins_mod

    registry = joins_mod.registered_joins()
    expected = {
        "gcounter", "pncounter", "lww", "lww_packed", "mvregister",
        "token_plane", "ew_flag", "dw_flag", "gset", "twopset",
        "orset", "rseq", "oplog", "compactlog",
        # derived composites (crdt_tpu.models.composite): full citizens of
        # the static gate — CRDT101-103 on the composed jaxpr, CRDT104 on
        # metadata propagation
        "mapof(pncounter)", "lexicographic(lww,mvregister)",
        "semidirect(gcounter,pncounter)", "product(gcounter,pncounter)",
    }
    assert expected <= set(registry)
    # every registration now carries neutral + rand: the registry is
    # sufficient to drive converge() and the ACI law sweep on its own
    for name, spec in registry.items():
        assert spec.neutral is not None, name
        assert spec.rand is not None, name
    assert jaxpr_checks.check_registered_joins(analysis.repo_root()) == []


# --------------------------------------------------------------- baseline

def test_fingerprint_survives_line_drift():
    a = Finding(rule="CRDT003", path="crdt_tpu/ops/x.py", line=10,
                message="m", scope="f", detail="np.asarray(x)")
    b = Finding(rule="CRDT003", path="crdt_tpu/ops/x.py", line=99,
                message="m", scope="f", detail="np.asarray(x)")
    assert baseline.fingerprint(a) == baseline.fingerprint(b)


def test_baseline_diff_flags_new_findings(tmp_path):
    known = Finding(rule="CRDT003", path="a.py", line=1, message="m",
                    scope="f", detail="d")
    bl = tmp_path / "baseline.json"
    baseline.save([known], bl)
    fresh = Finding(rule="CRDT004", path="b.py", line=2, message="m2",
                    scope="g", detail="e")
    new, stale = baseline.diff([known, fresh], bl)
    assert [f.rule for f in new] == ["CRDT004"]
    assert stale == []
    new2, stale2 = baseline.diff([fresh], bl)
    assert [f.rule for f in new2] == ["CRDT004"]
    assert [e["rule"] for e in stale2] == ["CRDT003"]


def test_tree_is_clean_against_committed_baseline():
    """What CI's `--check-baseline` enforces: zero new findings on the
    current tree vs crdt_tpu/analysis/baseline.json."""
    findings = analysis.run_all()
    new, _stale = baseline.diff(findings)
    assert new == [], "\n".join(f.render() for f in new)
    # and nothing in the tree is error-severity (errors are fixed, not
    # baselined — the baseline holds triaged warns only)
    assert [f for f in findings if f.severity == "error"] == []


def test_cli_check_baseline_exit_codes(tmp_path, monkeypatch):
    from crdt_tpu.analysis import __main__ as cli

    # a defect-free fixture tree: exit 0 even with an empty baseline
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x + 1\n")
    empty_bl = tmp_path / "bl.json"
    assert cli.main([str(clean), "--no-jaxpr", "--check-baseline",
                     "--baseline", str(empty_bl)]) == 0

    # inject a fixture defect: the gate must go red
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def poll(u):\n"
        "    try:\n"
        "        fetch(u)\n"
        "    except Exception:\n"
        "        pass\n")
    assert cli.main([str(bad), "--no-jaxpr", "--check-baseline",
                     "--baseline", str(empty_bl)]) == 1


# ------------------------------------------------------------------ SARIF

def test_sarif_output_shape(tmp_path):
    from crdt_tpu.analysis import __main__ as cli

    bad = tmp_path / "bad.py"
    bad.write_text(
        "def poll(u):\n"
        "    try:\n"
        "        fetch(u)\n"
        "    except Exception:\n"
        "        pass\n")
    out = tmp_path / "out.sarif"
    assert cli.main([str(bad), "--no-jaxpr", "--sarif", str(out)]) == 1
    import json

    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "crdtlint"
    (res,) = run["results"]
    assert res["ruleId"] == "CRDT004"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] >= 1
    # annotation identity rides the baseline fingerprint, so it survives
    # line drift exactly like the suppression ratchet
    assert res["partialFingerprints"]["crdtlint/v1"]
    # the referenced rule is declared in the driver's rule table
    rules = run["tool"]["driver"]["rules"]
    assert rules[res["ruleIndex"]]["id"] == "CRDT004"


def test_hazard_and_verify_rules_are_listed():
    """CRDT105-107 (semantic hazards) and CRDT301/302 (verify gate) are
    first-class rules: documented, severity-mapped, CLI-listable."""
    for rule in ("CRDT105", "CRDT106", "CRDT107", "CRDT301", "CRDT302"):
        assert rule in analysis.RULES
    assert analysis.SEVERITY["CRDT105"] == "error"
    assert analysis.SEVERITY["CRDT106"] == "error"
    assert analysis.SEVERITY["CRDT107"] == "warn"
    assert analysis.SEVERITY["CRDT301"] == "error"
    assert analysis.SEVERITY["CRDT302"] == "error"
