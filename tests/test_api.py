"""API-layer tests: ReplicaNode semantics, LocalCluster convergence, the
five-endpoint HTTP shim, and the soak harness with fault injection —
the reference's validation story (SURVEY.md §4), automated."""
import json
import urllib.request

import pytest

from crdt_tpu.api.cluster import LocalCluster
from crdt_tpu.api.http_shim import HttpCluster
from crdt_tpu.harness.workload import WorkloadGenerator
from crdt_tpu.oracle import OracleReplica, Quirks
from crdt_tpu.utils.config import ClusterConfig


def _small_config(**kw):
    kw.setdefault("n_replicas", 3)
    kw.setdefault("log_capacity", 64)
    return ClusterConfig(**kw)


def test_node_write_read_counter_semantics():
    c = LocalCluster(_small_config(n_replicas=1))
    n = c.nodes[0]
    assert n.add_command({"x": "5"})
    assert n.add_command({"x": "-3", "y": "zz"})
    state = n.get_state()
    assert state == {"x": "2", "y": "zz"}


def test_node_down_rejects_and_recovers():
    c = LocalCluster(_small_config(n_replicas=2))
    a, b = c.nodes
    a.add_command({"k": "1"})
    b.set_alive(False)
    assert not b.add_command({"k": "2"})
    assert b.get_state() is None
    assert b.gossip_payload() is None
    assert not c.gossip_once(1)  # dead puller skips
    b.set_alive(True)
    b.receive(a.gossip_payload())  # catch-up: one full-state merge
    assert b.get_state() == {"k": "1"}


def test_cluster_converges_and_matches_oracle():
    cfg = _small_config(n_replicas=4, seed=3)
    c = LocalCluster(cfg)
    wl = WorkloadGenerator(cfg)
    oracles = [OracleReplica(r, Quirks()) for r in range(4)]

    for i in range(30):
        cmd, target = wl.next_command()
        ts = 1000 + i
        c.nodes[target].add_command(cmd, ts=ts)
        oracles[target].add_command(cmd, ts=ts)

    for _ in range(100):
        c.tick()
        if c.converged():
            break
    assert c.converged()
    expect = OracleReplica.converged_state(oracles)
    assert c.nodes[0].get_state() == expect


def test_log_growth_beyond_initial_capacity():
    c = LocalCluster(_small_config(n_replicas=1, log_capacity=8))
    n = c.nodes[0]
    for i in range(50):  # 50 ops >> capacity 8: must grow, not drop
        assert n.add_command({"k": "1"}, ts=i)
    assert n.get_state() == {"k": "50"}
    assert n.log.capacity >= 50


def test_reference_topology_gossip_still_converges():
    # friend list includes self + dead ports (quirk §0.1.9): ~50% of pulls
    # are skipped, but convergence must still happen (just slower).
    cfg = _small_config(n_replicas=3, reference_topology=True, seed=5)
    c = LocalCluster(cfg)
    for i, node in enumerate(c.nodes):
        node.add_command({"abc"[i]: "7"}, ts=100 + i)
    for _ in range(200):
        c.tick()
        if c.converged():
            break
    assert c.converged()
    assert c.nodes[0].get_state() == {"a": "7", "b": "7", "c": "7"}


@pytest.fixture
def http_cluster():
    cluster = LocalCluster(_small_config(n_replicas=3))
    http = HttpCluster(cluster)
    ports = http.start()
    yield cluster, [f"http://127.0.0.1:{p}" for p in ports]
    http.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def test_http_five_endpoint_surface(http_cluster):
    cluster, urls = http_cluster

    # POST /data + GET /data
    req = urllib.request.Request(
        urls[0] + "/data", data=json.dumps({"a": "4"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        assert (r.status, r.read().decode()) == (200, "Inserted")
    assert json.loads(_get(urls[0] + "/data")[1]) == {"a": "4"}

    # GET /ping
    assert _get(urls[1] + "/ping") == (200, "Pong")

    # GET /gossip -> feed to another node via its receive path
    status, body = _get(urls[0] + "/gossip")
    assert status == 200
    cluster.nodes[1].receive(json.loads(body))
    assert cluster.nodes[1].get_state() == {"a": "4"}

    # GET /condition (fixed routing: path param, quirk §0.1.7)
    assert _get(urls[2] + "/condition/false")[0] == 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(urls[2] + "/ping")
    assert ei.value.code == 502
    assert _get(urls[2] + "/condition/true")[0] == 200
    assert _get(urls[2] + "/ping") == (200, "Pong")


def test_http_malformed_body_500s(http_cluster):
    _, urls = http_cluster
    req = urllib.request.Request(
        urls[0] + "/data", data=b"{not json", method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 500


def test_soak_with_fault_injection():
    """The reference's eyeball-soak (main.go:273-314 + /condition), as an
    automated assertion: random workload, a replica dies mid-run, revives,
    and the swarm still converges to the oracle ground truth."""
    cfg = _small_config(n_replicas=4, seed=11)
    c = LocalCluster(cfg)
    wl = WorkloadGenerator(cfg)
    oracles = [OracleReplica(r, Quirks()) for r in range(4)]

    def write(i):
        cmd, target = wl.next_command()
        if c.nodes[target].add_command(cmd, ts=2000 + i):
            oracles[target].add_command(cmd, ts=2000 + i)

    for i in range(20):
        write(i)
    c.nodes[2].set_alive(False)
    for i in range(20, 40):
        write(i)  # writes to node 2 bounce (502), like the real cluster
        if i % 4 == 0:
            c.tick()
    c.nodes[2].set_alive(True)
    for _ in range(100):
        c.tick()
        if c.converged():
            break
    assert c.converged()
    assert c.nodes[2].get_state() == OracleReplica.converged_state(oracles)
    snap = c.metrics.snapshot()
    assert snap["gossip_rounds"] > 0 and "merge_p50_ms" in snap


def test_go_wire_millisecond_keys_accepted():
    """A Go peer's gossip payload keys are absolute UnixMilli ints
    (main.go:187) — they must rebase onto the node's int32 window."""
    c = LocalCluster(_small_config(n_replicas=1))
    n = c.nodes[0]
    go_ts = n.clock.epoch_ms + 1234  # what a contemporary Go peer would send
    n.receive({str(go_ts): {"x": "7"}})
    assert n.get_state() == {"x": "7"}
    with pytest.raises(ValueError):
        n.receive({str(n.clock.epoch_ms + 2**40): {"x": "1"}})


def test_wire_roundtrip_across_different_epochs():
    """Two nodes with different clock epochs (separate processes) must
    exchange ops without int32 overflow or identity drift."""
    from crdt_tpu.api.node import ReplicaNode
    from crdt_tpu.utils.clock import HostClock

    a = ReplicaNode(rid=0, capacity=32, clock=HostClock(epoch_ms=1_700_000_000_000))
    b = ReplicaNode(rid=1, capacity=32, clock=HostClock(epoch_ms=1_700_000_500_000))
    a.add_command({"x": "5"}, ts=100)
    b.add_command({"x": "3"}, ts=200)
    a.receive(b.gossip_payload())
    b.receive(a.gossip_payload())
    assert a.get_state() == b.get_state() == {"x": "8"}
    # re-delivery is a no-op (identity stable through rebasing)
    a.receive(b.gossip_payload())
    assert a.get_state() == {"x": "8"}
