"""Device-mesh sharded anti-entropy tests (parallel.meshplane).

The mesh plane's whole claim is "same bits, fewer dispatches": ONE
compiled step folds every keyspace shard lane, and each lane's merged
log / vv / state is bit-identical to what S independent host dispatches
would have produced.  These tests pin both halves:

* randomized multi-tenant traces driven through a mesh keyspace and a
  host-path twin, compared per shard down to the raw OpLog columns —
  for every engine (the auto-selected one, the shard_map compat-shim
  fallback, and single-device vmap fusion);
* exactly ONE label-free `merge_dispatches` tick per converge (vs S on
  the host path), with per-shard attribution surviving as
  `merge_dispatches{shard=i}` labels — asserted on a rendered AND a
  served (real socket) /metrics scrape;
* corrupt-shard isolation: a payload that fails structural validation
  quarantines ITS lane while the siblings still fold in the same step;
* engine failure lands every lane via its own inline host dispatch
  (commit_inline) — bits still right, `meshplane_fallbacks` ticks.

conftest.py pins JAX_PLATFORMS=cpu with 8 emulated host devices, so
the pjit/shard_map engines get a real multi-device mesh under CI.
"""
from __future__ import annotations

import json
import random
import re

import jax
import numpy as np
import pytest

from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.keyspace import ShardedKeyspace, qualify
from crdt_tpu.models import oplog
from crdt_tpu.parallel.meshplane import (MESH_MODES, MeshPlane,
                                         _mesh_divisor, select_engine)
from crdt_tpu.utils.clock import ManualClock
from crdt_tpu.utils.config import ClusterConfig
from crdt_tpu.utils.metrics import Metrics

N_SHARDS = 4
TENANTS = ("t-acme", "t-bravo", "t-noisy")
_COLS = ("ts", "rid", "seq", "key", "val", "payload", "is_num")


def _twin_keyspaces(n_shards: int = N_SHARDS, engine=None):
    """A mesh keyspace + a host-path twin sharing ONE ManualClock (same
    epoch => same rebased ts => bit-comparable logs).  ``engine`` pins a
    specific mesh engine via the MeshPlane override."""
    clock = ManualClock()
    host = ShardedKeyspace(rid=0, n_shards=n_shards, capacity=64,
                           metrics=Metrics(), clock=clock, mesh="off")
    mesh = ShardedKeyspace(rid=0, n_shards=n_shards, capacity=64,
                           metrics=Metrics(), clock=clock, mesh="on")
    if engine is not None:
        mesh._meshplane = MeshPlane(
            n_shards, mode="on", metrics=mesh.shards[0].metrics,
            engine=engine)
    return host, mesh, clock


def _writers(ks: ShardedKeyspace, clock, rids=(100, 101)):
    """Per-(shard, rid) writer nodes on the SAME clock — the gossip
    sources whose payloads both twins fold."""
    return {(s, r): ReplicaNode(rid=r, capacity=64, clock=clock)
            for s in range(ks.n_shards) for r in rids}


def _random_round(rng, ks, writers, clock, n_ops=8):
    """One gossip round: random tenant-qualified writes land on the
    writer owning their shard; returns one payload per shard (None for
    shards nothing routed to this round)."""
    rids = sorted({r for (_, r) in writers})
    for _ in range(n_ops):
        tenant = rng.choice(TENANTS)
        key = f"k{rng.randrange(12)}"
        val = f"v{rng.randrange(1000)}"
        shard = ks.shard_of(tenant, key)
        writers[(shard, rng.choice(rids))].add_commands(
            [{qualify(tenant, key): val}])
        clock.advance(rng.randrange(1, 3))
    payloads = []
    for s in range(ks.n_shards):
        merged = {}
        for r in rids:
            p = writers[(s, r)].gossip_payload()
            if p:
                merged.update(p)
        payloads.append(merged or None)
    return payloads


def _assert_shards_bit_equal(host: ShardedKeyspace, mesh: ShardedKeyspace):
    """state + vv + the live prefix of every raw OpLog column, per shard."""
    for i, (h, m) in enumerate(zip(host.shards, mesh.shards)):
        assert m.get_state() == h.get_state(), f"shard {i} state diverged"
        assert m.version_vector() == h.version_vector(), \
            f"shard {i} vv diverged"
        n_h, n_m = int(oplog.size(h.log)), int(oplog.size(m.log))
        assert n_m == n_h, f"shard {i} live rows {n_m} != {n_h}"
        for col in _COLS:
            a = np.asarray(getattr(h.log, col))[:n_h]
            b = np.asarray(getattr(m.log, col))[:n_h]
            assert np.array_equal(a, b), \
                f"shard {i} column {col} not bit-identical"


# ---- engine selection ----

def test_mesh_divisor():
    assert _mesh_divisor(4, 8) == 4
    assert _mesh_divisor(6, 4) == 3
    assert _mesh_divisor(5, 4) == 1
    assert _mesh_divisor(8, 8) == 8


def test_select_engine_modes():
    with pytest.raises(ValueError):
        select_engine(4, "bogus")
    assert select_engine(4, "off") is None
    assert select_engine(0, "on") is None
    # auto refuses to fuse a single lane — nothing to amortize
    assert select_engine(1, "auto") is None
    # "on" always fuses; with the conftest's 8 emulated devices and a
    # lane count they divide, a sharded engine (pjit preferred, else the
    # shard_map compat shim) must be picked over plain vmap
    eng = select_engine(4, "on")
    assert eng in ("pjit", "shard_map", "vmap")
    if len(jax.devices()) >= 2:
        assert eng in ("pjit", "shard_map")
    # a prime lane count can't split across the mesh: vmap fusion
    assert select_engine(7, "on") == "vmap" or len(jax.devices()) >= 7


def test_config_knob_validated():
    assert ClusterConfig(keyspace_mesh="on").keyspace_mesh == "on"
    with pytest.raises(ValueError):
        ClusterConfig(keyspace_mesh="bogus")
    for mode in MESH_MODES:
        ClusterConfig(keyspace_mesh=mode)


# ---- bit-parity: mesh vs host twin, every engine ----

@pytest.mark.parametrize("engine", [None, "shard_map", "vmap"])
def test_mesh_parity_randomized_multitenant(engine):
    """Randomized multi-tenant trace: after every fused converge, each
    mesh shard is bit-identical (state, vv, all 7 raw OpLog columns) to
    its host-path twin.  ``None`` runs whatever select_engine picks in
    this environment; shard_map exercises the compat-shim fallback and
    vmap the single-device fusion."""
    host, mesh, clock = _twin_keyspaces(engine=engine)
    assert mesh.mesh_active
    if engine is not None:
        assert mesh.mesh_engine == engine
    writers = _writers(mesh, clock)
    rng = random.Random(1234)
    for step in range(6):
        payloads = _random_round(rng, mesh, writers, clock)
        for i, p in enumerate(payloads):
            if p is not None:
                host.receive(i, p)
        results = mesh.receive_all(payloads)
        assert all(isinstance(r, int) for r in results)
        _assert_shards_bit_equal(host, mesh)
    assert mesh.state() == host.state()
    assert mesh.state()  # the trace actually wrote something


# ---- one dispatch per step + per-shard attribution ----

def test_one_dispatch_per_step_and_shard_labels():
    """The perf pin: a fused converge costs ONE label-free
    merge_dispatches tick regardless of S, where the host twin pays one
    per shard — while the per-shard labeled counters tick identically
    on both paths."""
    host, mesh, clock = _twin_keyspaces()
    writers = _writers(mesh, clock)
    rng = random.Random(7)
    payloads = _random_round(rng, mesh, writers, clock, n_ops=16)
    n_nonempty = sum(1 for p in payloads if p is not None)
    assert n_nonempty == N_SHARDS  # 16 ops over 4 shards: all hit

    before_m = mesh.shards[0].metrics._counts.get("merge_dispatches", 0)
    before_h = host.shards[0].metrics._counts.get("merge_dispatches", 0)
    mesh.receive_all(payloads)
    for i, p in enumerate(payloads):
        if p is not None:
            host.receive(i, p)
    mesh_ticks = (mesh.shards[0].metrics._counts["merge_dispatches"]
                  - before_m)
    host_ticks = (host.shards[0].metrics._counts["merge_dispatches"]
                  - before_h)
    assert mesh_ticks == 1, "mesh step must be ONE device dispatch"
    assert host_ticks == n_nonempty, "host path pays one per shard"

    # per-shard attribution is path-independent: every folded lane ticks
    # merge_dispatches{shard=i} and union_path{path=sort,shard=i} once,
    # on the rendered scrape of BOTH twins
    for ks in (mesh, host):
        text = ks.shards[0].metrics.registry.render_prometheus()
        for i in range(N_SHARDS):
            assert f'crdt_merge_dispatches_total{{shard="{i}"}} 1' in text
            assert (f'crdt_union_path_total{{path="sort",shard="{i}"}} 1'
                    in text)


def test_zero_fresh_converge_skips_device():
    """Idempotent redelivery: a round where every lane folds nothing
    commits inline — no device dispatch at all."""
    host, mesh, clock = _twin_keyspaces()
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(3), mesh, writers, clock)
    mesh.receive_all(payloads)
    before = mesh.shards[0].metrics._counts["merge_dispatches"]
    results = mesh.receive_all(payloads)  # pure redelivery
    assert all(r == 0 for r in results)
    assert mesh.shards[0].metrics._counts["merge_dispatches"] == before
    assert all(isinstance(r, int) for r in
               mesh.receive_all([None] * N_SHARDS))
    assert mesh.shards[0].metrics._counts["merge_dispatches"] == before


# ---- corrupt-shard isolation inside the fused step ----

def test_corrupt_shard_isolated_siblings_fold():
    """A payload that fails structural validation quarantines its OWN
    lane (error-string result, shard state untouched) while the
    siblings still converge — in the same single dispatch."""
    host, mesh, clock = _twin_keyspaces()
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(11), mesh, writers, clock,
                             n_ops=16)
    corrupt_shard = 1
    payloads[corrupt_shard] = {"nemesis:corrupt:key": {"a": "b"}}
    for i, p in enumerate(payloads):
        if i != corrupt_shard and p is not None:
            host.receive(i, p)

    before = mesh.shards[0].metrics._counts.get("merge_dispatches", 0)
    results = mesh.receive_all(payloads, quarantine=True)
    assert isinstance(results[corrupt_shard], str)
    assert "ValueError" in results[corrupt_shard]
    for i, r in enumerate(results):
        if i != corrupt_shard:
            assert isinstance(r, int) and r > 0, f"sibling {i} didn't fold"
    # the corrupt lane rode along empty: its shard matches the host twin
    # (which never saw the corrupt payload), and the siblings match too
    _assert_shards_bit_equal(host, mesh)
    assert (mesh.shards[0].metrics._counts["merge_dispatches"]
            - before) == 1

    # without quarantine the same payload raises — after every lane's
    # lock was released (a second receive_all must not deadlock)
    with pytest.raises(ValueError):
        mesh.receive_all(payloads, quarantine=False)
    mesh.receive_all([None] * N_SHARDS)


# ---- engine failure: inline host fallback ----

def test_step_failure_falls_back_to_inline_commits():
    """If the compiled step blows up, every lane lands via its own
    inline host dispatch: bits identical to the host path, locks
    released, meshplane_fallbacks ticked."""
    host, mesh, clock = _twin_keyspaces()
    plane = mesh._plane()

    def boom(capacity, batch_cap):
        raise RuntimeError("injected engine failure")

    plane._step_for = boom
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(5), mesh, writers, clock)
    for i, p in enumerate(payloads):
        if p is not None:
            host.receive(i, p)
    results = mesh.receive_all(payloads)
    assert all(isinstance(r, int) for r in results)
    _assert_shards_bit_equal(host, mesh)
    counts = mesh.shards[0].metrics._counts
    assert counts["meshplane_fallbacks"] == 1
    # fallback pays the per-lane dispatches (the host path's cost)
    assert counts["merge_dispatches"] == sum(
        1 for p in payloads if p is not None)


def test_lane_count_mismatch_aborts_cleanly():
    host, mesh, clock = _twin_keyspaces()
    with pytest.raises(ValueError):
        mesh.receive_all([None] * (N_SHARDS + 1))
    plane = mesh._plane()
    pendings = [s.merge_begin([]) for s in mesh.shards[:2]]
    with pytest.raises(ValueError):
        plane.converge(pendings)
    # locks were released by the abort: lanes still usable
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(2), mesh, writers, clock)
    assert sum(r for r in mesh.receive_all(payloads)
               if isinstance(r, int)) > 0


# ---- failure paths must never leak a lane's node lock ----

def _assert_no_lock_leak(ks: ShardedKeyspace):
    """Every shard's node lock is free (non-blocking probe — a leaked
    lock fails the assert instead of hanging the test run)."""
    for i, shard in enumerate(ks.shards):
        assert shard._lock.acquire(blocking=False), f"shard {i} lock leaked"
        shard._lock.release()


def test_adoption_failure_quarantines_lane_without_lock_leak():
    """A payload that PASSES structural validation but fails at ADOPTION
    time inside merge_begin (non-trivial frontier with no __summary__ —
    receiver-state dependent, so validate_payload can't pre-screen it)
    must not leak the earlier lanes' node locks: with quarantine it
    becomes that lane's error-string result while every sibling still
    folds; without quarantine it raises only after every already-held
    lane landed inline."""
    host, mesh, clock = _twin_keyspaces()
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(23), mesh, writers, clock,
                             n_ops=16)
    bad_shard = 2
    bad = {"__frontier__": {"7": 5}}  # truncated: frontier, no summary
    assert mesh.shards[bad_shard].validate_payload(bad) is None
    payloads[bad_shard] = bad
    for i, p in enumerate(payloads):
        if i != bad_shard and p is not None:
            host.receive(i, p)

    results = mesh.receive_all(payloads, quarantine=True)
    assert isinstance(results[bad_shard], str)
    assert "__summary__" in results[bad_shard]
    for i, r in enumerate(results):
        if i != bad_shard and payloads[i] is not None:
            assert isinstance(r, int) and r > 0, f"sibling {i} didn't fold"
    _assert_no_lock_leak(mesh)
    # the quarantined lane rode along empty: bit-equal to the host twin
    # (which never saw the bad payload)
    _assert_shards_bit_equal(host, mesh)

    # without quarantine the adoption failure propagates — but the lanes
    # begun before it landed inline and released their locks first
    payloads2 = _random_round(random.Random(24), mesh, writers, clock)
    payloads2[bad_shard] = dict(bad)
    with pytest.raises(ValueError, match="__summary__"):
        mesh.receive_all(payloads2, quarantine=False)
    _assert_no_lock_leak(mesh)
    assert all(isinstance(r, int)
               for r in mesh.receive_all([None] * N_SHARDS))


def test_commit_failure_still_commits_sibling_lanes():
    """If ONE lane's post-dispatch commit raises (accounting failure),
    converge still commits every sibling's fused output before
    re-raising — no sibling is left with its node lock held and its
    host indexes ahead of its log."""
    host, mesh, clock = _twin_keyspaces()
    writers = _writers(mesh, clock)
    payloads = _random_round(random.Random(31), mesh, writers, clock,
                             n_ops=16)
    for i, p in enumerate(payloads):
        if p is not None:
            host.receive(i, p)
    bad = next(i for i, p in enumerate(payloads) if p is not None)

    def boom():
        raise RuntimeError("injected commit failure")

    mesh.shards[bad]._count_lane_fold = boom
    try:
        with pytest.raises(RuntimeError, match="injected commit failure"):
            mesh.receive_all(payloads)
    finally:
        del mesh.shards[bad]._count_lane_fold  # restore the class method
    _assert_no_lock_leak(mesh)
    # every lane's fused output committed (the failing lane's log was
    # rebound before its accounting blew up), so the twin still matches
    _assert_shards_bit_equal(host, mesh)
    # and the keyspace folds normally on the next round
    assert all(isinstance(r, int)
               for r in mesh.receive_all([None] * N_SHARDS))


def test_fused_flush_converge_failure_fails_claims_and_releases_lanes():
    """flush_all_fused: a converge that re-raises (one lane's commit
    failed) must fail every outstanding drain claim — waiting tickets
    observe the error instead of hanging — and release every drain slot
    and node lock, leaving the door fully usable."""
    from crdt_tpu.keyspace import KeyspaceFrontDoor

    clock = ManualClock()
    mesh = ShardedKeyspace(rid=0, n_shards=N_SHARDS, capacity=64,
                           metrics=Metrics(), clock=clock, mesh="on")
    door = KeyspaceFrontDoor(mesh, max_batch=1024)
    groups = {}
    for i in range(16):
        key = f"k{i}"
        shard = mesh.shard_of("t-acme", key)
        groups.setdefault(shard, []).append(
            (None, {qualify("t-acme", key): f"v{i}"}, "t-acme"))
    lane_tickets = door._submit_groups(groups, "t-acme")
    bad = next(iter(groups))

    def boom():
        raise RuntimeError("injected commit failure")

    mesh.shards[bad]._count_lane_fold = boom
    try:
        with pytest.raises(RuntimeError, match="injected commit failure"):
            door.flush_all()
    finally:
        del mesh.shards[bad]._count_lane_fold
    for _, ticket in lane_tickets:
        assert ticket.done, "a drained ticket was left unresolved"
        with pytest.raises(RuntimeError, match="injected commit failure"):
            ticket.wait(0)
    _assert_no_lock_leak(mesh)
    for lane in door.lanes:
        assert lane._drain_lock.acquire(blocking=False), \
            f"lane {lane.name} drain slot leaked"
        lane._drain_lock.release()
    # the door keeps admitting and draining after the failed fused flush
    assert door.admit_kv("t-acme", "fresh-key", "fresh-val",
                         timeout=5.0) is not None
    assert mesh.get("t-acme", "fresh-key") == "fresh-val"


# ---- served /metrics scrape over a real socket ----

def test_served_scrape_shows_per_shard_counters():
    """End-to-end: a mesh-path ks_pull over real sockets, then the
    puller's served GET /metrics carries the per-shard labeled
    merge_dispatches/union_path counters next to the ONE label-free
    fused-dispatch tick."""
    import threading
    import urllib.request

    from crdt_tpu.api.net import NodeHost, RemotePeer
    from crdt_tpu.keyspace import TENANT_HEADER

    cfg = ClusterConfig(keyspace_shards=N_SHARDS, keyspace_capacity=64,
                        keyspace_mesh="on")
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[], config=cfg)
    assert b.keyspace.mesh_active
    threads = []
    for h in (a, b):
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()
        threads.append(t)
    try:
        before = b.node.metrics._counts.get("merge_dispatches", 0)
        body = {f"k{i}": f"v{i}" for i in range(16)}
        req = urllib.request.Request(
            a.url + "/data", data=json.dumps(body).encode(), method="POST")
        req.add_header(TENANT_HEADER, "t-acme")
        assert urllib.request.urlopen(req, timeout=5).status == 200
        assert b.agent.ks_pull(RemotePeer(a.url)) == 16
        assert b.keyspace.tenant_state("t-acme") == body
        # one fused dispatch for the whole pull round
        assert (b.node.metrics._counts["merge_dispatches"] - before) == 1
        text = RemotePeer(b.url).metrics_text()
        for i in range(N_SHARDS):
            assert f'crdt_merge_dispatches_total{{shard="{i}"}}' in text
            assert (f'crdt_union_path_total{{path="sort",shard="{i}"}}'
                    in text)
        # the label-free fused tick serves alongside the labeled ones
        assert re.search(r"^crdt_merge_dispatches_total \d", text,
                         re.MULTILINE)
    finally:
        for h in (a, b):
            h._server.shutdown()
            h._server.server_close()
