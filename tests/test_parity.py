"""Bit-exact parity: the TPU OpLog path vs the pure-Python oracle.

Strategy (SURVEY.md §4): random workloads of reference-shaped commands
(single-key string deltas, occasional non-numeric values, multi-key commands)
are applied to both an oracle swarm (quirks OFF = fixed semantics) and the
array-encoded OpLog replicas; after every merge schedule the materialized
views must match string-for-string."""
import numpy as np
import pytest

from crdt_tpu.models import oplog
from crdt_tpu.oracle import OracleReplica, Quirks
from crdt_tpu.utils.intern import Interner, encode_value, parse_go_int

ALPHABET = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ1234567890"


class DeviceReplica:
    """Thin host wrapper pairing an OpLog with the interners, mirroring the
    oracle's add_command/gossip/receive surface for the tests."""

    def __init__(self, rid: int, capacity: int, keys: Interner, values: Interner):
        self.rid = rid
        self.keys = keys
        self.values = values
        self.log = oplog.empty(capacity)
        self._seq = 0

    def add_command(self, cmd: dict, ts: int) -> None:
        seq = self._seq
        self._seq += 1
        rows = {"ts": [], "rid": [], "seq": [], "key": [], "val": [], "payload": [], "is_num": []}
        for k, v in cmd.items():
            val, payload, is_num = encode_value(v, self.values)
            rows["ts"].append(ts)
            rows["rid"].append(self.rid)
            rows["seq"].append(seq)
            rows["key"].append(self.keys.intern(k))
            rows["val"].append(val)
            rows["payload"].append(payload)
            rows["is_num"].append(is_num)
        ops = {
            n: np.asarray(c, bool if n == "is_num" else np.int32)
            for n, c in rows.items()
        }
        self.log = oplog.append_batch(self.log, ops, batch_capacity=len(cmd))

    def receive(self, remote_log: oplog.OpLog) -> None:
        self.log = oplog.merge(self.log, remote_log)

    def materialized(self) -> dict:
        """Decode KVState back to the reference's {key: string} map."""
        kv = oplog.rebuild(self.log, n_keys=len(self.keys))
        return oplog.materialize(kv, self.keys, self.values)


def _rand_cmd(rng, multi_key_p=0.2, non_num_p=0.15, odd_num_p=0.1):
    n_keys = 1 + int(rng.random() < multi_key_p)
    cmd = {}
    while len(cmd) < n_keys:
        k = ALPHABET[rng.integers(0, len(ALPHABET))]
        u = rng.random()
        if u < non_num_p:
            cmd[k] = "s" + str(int(rng.integers(0, 100)))  # non-numeric value
        elif u < non_num_p + odd_num_p:
            # numeric strings Atoi accepts but Itoa would not emit — these
            # must survive verbatim while they are a key's only numeric op
            cmd[k] = rng.choice(["007", "+7", "-0", "+0", "000"])
        else:
            # reference workload delta distribution (main.go:275-282)
            cmd[k] = str(int(rng.integers(0, 10)) - 20)
    return cmd


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_device_matches_oracle_random_workload(seed):
    rng = np.random.default_rng(seed)
    n_replicas, n_writes, capacity = 4, 40, 128
    keys, values = Interner(), Interner()
    dev = [DeviceReplica(r, capacity, keys, values) for r in range(n_replicas)]
    ora = [OracleReplica(r, Quirks()) for r in range(n_replicas)]

    ts = 0
    for w in range(n_writes):
        ts += int(rng.integers(0, 3))  # deliberately allow same-ms collisions
        r = int(rng.integers(0, n_replicas))
        cmd = _rand_cmd(rng)
        dev[r].add_command(cmd, ts)
        ora[r].add_command(cmd, ts)

        if w % 5 == 4:  # a gossip pull: random (dst, src) pair
            dst, src = rng.choice(n_replicas, size=2, replace=False)
            dev[dst].receive(dev[src].log)
            ora[dst].receive(ora[src].gossip_payload())

    for r in range(n_replicas):
        assert dev[r].materialized() == ora[r].rebuilt_state(), f"replica {r}"


def test_full_convergence_matches_oracle():
    rng = np.random.default_rng(42)
    n_replicas, capacity = 3, 64
    keys, values = Interner(), Interner()
    dev = [DeviceReplica(r, capacity, keys, values) for r in range(n_replicas)]
    ora = [OracleReplica(r, Quirks()) for r in range(n_replicas)]
    for w in range(20):
        r = int(rng.integers(0, n_replicas))
        cmd = _rand_cmd(rng)
        dev[r].add_command(cmd, ts=w)
        ora[r].add_command(cmd, ts=w)

    # all-pairs gossip twice = guaranteed fixpoint for 3 replicas
    for _ in range(2):
        for dst in range(n_replicas):
            for src in range(n_replicas):
                if dst != src:
                    dev[dst].receive(dev[src].log)
                    ora[dst].receive(ora[src].gossip_payload())

    expect = OracleReplica.converged_state(ora)
    for r in range(n_replicas):
        assert dev[r].materialized() == expect
        assert ora[r].rebuilt_state() == expect


def test_parse_go_int_matches_go_atoi():
    assert parse_go_int("42") == 42
    assert parse_go_int("-13") == -13
    assert parse_go_int("+7") == 7
    assert parse_go_int("007") == 7
    for bad in ["", " 1", "1 ", "1_0", "0x10", "1.5", "abc", "--1", "+", "٣"]:
        assert parse_go_int(bad) is None, bad
