"""Consistency plane: stability-frontier math, session tokens, quorum
reads/CAS — the deterministic (fake clock, fake peers) half of what the
nemesis --gc / --strong soaks audit end-to-end.

Every wait loop in the plane takes injectable ``clock``/``sleep``, so the
timeout paths here run in microseconds of wall time: the fake clock only
advances when the code under test sleeps.
"""
from __future__ import annotations

import pytest

from crdt_tpu.api.node import ReplicaNode, stable_frontier_host
from crdt_tpu.consistency import (
    CasConflict,
    ConsistencyPlane,
    ConsistencyUnavailable,
    StabilityTracker,
    decode_summary,
    decode_token,
    encode_summary,
    encode_token,
    mint_token,
    token_join,
    vv_dominates,
    wait_for_dominance,
)
from crdt_tpu.ingest.admission import IngestFrontDoor
from crdt_tpu.obs.events import EventLog


class FakeTime:
    """Manual clock + sleep: time advances only when the code sleeps."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = 0

    def now(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.sleeps += 1
        self.t += max(dt, 1e-6)


class FakePeer:
    """RemotePeer stand-in over a backing ReplicaNode, with switches for
    every failure posture the plane distinguishes: an OPEN breaker
    (``backed``), a dead transport (``down``), a peer that acks vv probes
    but cannot serve deltas (``serve_deltas=False``), and a peer that
    drops synchronous pushes (``accept_push=False``)."""

    def __init__(self, node: ReplicaNode, url: str = "peer"):
        self.node = node
        self.url = url
        self.backed = False
        self.down = False
        self.serve_deltas = True
        self.accept_push = True
        self.vv_calls = 0

    def backed_off(self) -> bool:
        return self.backed

    def version_vector(self):
        self.vv_calls += 1
        if self.down:
            return None
        return self.node.vv_snapshot()

    def gossip_payload(self, since=None):
        if self.down or not self.serve_deltas:
            return None
        return self.node.gossip_payload(since=since)

    def push_payload(self, payload) -> bool:
        if self.down or not self.accept_push:
            return False
        self.node.receive(payload)
        return True


def mk_node(rid: int) -> ReplicaNode:
    return ReplicaNode(rid=rid, capacity=64)


def mk_plane(node: ReplicaNode, peers, ft: FakeTime, **kw) -> ConsistencyPlane:
    kw.setdefault("strong_timeout", 0.2)
    kw.setdefault("session_timeout", 0.2)
    kw.setdefault("poll", 0.02)
    return ConsistencyPlane(node, peers=lambda: peers,
                            clock=ft.now, sleep=ft.sleep, **kw)


class StubNode:
    """vv_snapshot-only node for pure frontier-math tests."""

    def __init__(self, vv, frontier=None):
        self.vv = dict(vv)
        self.frontier = dict(frontier or {})

    def vv_snapshot(self):
        return dict(self.vv), dict(self.frontier)


# ---------------------------------------------------------------- stability


def test_frontier_stalls_without_summaries():
    ft = FakeTime()
    ev = EventLog(node="t")
    tr = StabilityTracker(StubNode({0: 5}), ["a", "b"], clock=ft.now,
                          events=ev)
    assert tr.frontier() == {}
    assert tr.stale_members() == ["a", "b"]
    [rec] = ev.find(event="stability_stalled")
    assert rec["stale"] == ["a", "b"]


def test_frontier_is_pointwise_min_over_fleet():
    ft = FakeTime()
    tr = StabilityTracker(StubNode({0: 5, 1: 9}), ["a", "b"], clock=ft.now)
    tr.note("a", {0: 3, 1: 9}, {})
    tr.note("b", {0: 5, 1: 7}, {})
    # per-writer min across (local, a, b); writer 2 unseen anywhere
    assert tr.frontier() == {0: 3, 1: 7}


def test_frontier_partial_view_drops_unseen_writers():
    ft = FakeTime()
    tr = StabilityTracker(StubNode({0: 5, 2: 4}), ["a"], clock=ft.now)
    tr.note("a", {0: 2}, {})  # a has never heard from writer 2
    assert tr.frontier() == {0: 2}  # writer 2 min is -1 -> not stable


def test_frontier_stalls_on_stale_summary():
    ft = FakeTime()
    ev = EventLog(node="t")
    tr = StabilityTracker(StubNode({0: 5}), ["a"], max_staleness=10.0,
                          clock=ft.now, events=ev)
    tr.note("a", {0: 5}, {})
    assert tr.frontier() == {0: 5}
    ft.t += 11.0  # summary ages out; a live fleet would have refreshed it
    assert tr.stale_members() == ["a"]
    assert tr.frontier() == {}
    assert len(ev.find(event="stability_stalled")) == 1


def test_note_merges_monotone_under_reordering():
    ft = FakeTime()
    tr = StabilityTracker(StubNode({0: 9, 1: 9}), ["a"], clock=ft.now)
    tr.note("a", {0: 7, 1: 2}, {})
    tr.note("a", {0: 3, 1: 5}, {})  # delayed older summary arrives late
    # watermarks are monotone facts: pointwise max, never replacement
    assert tr.observed()["a"]["vv"] == {0: 7, 1: 5}
    assert tr.frontier() == {0: 7, 1: 5}


def test_stale_watermark_only_under_collects():
    # a frontier minted from old watermarks is <= the true stable
    # frontier — staleness can delay GC but never collect a live op
    ft = FakeTime()
    node = StubNode({0: 100})
    tr = StabilityTracker(node, ["a"], clock=ft.now)
    tr.note("a", {0: 40}, {})  # old view; a is really at 100 by now
    f = tr.frontier()
    assert f == {0: 40}
    assert all(s <= 100 for s in f.values())


def test_chain_rule_stalls_incomparable_fold():
    # a member's already-folded frontier is AHEAD of the candidate min:
    # minting would create an incomparable frontier generation
    assert stable_frontier_host(
        [{0: 5}, {0: 3}], [{0: 4}, {}]) == {}
    # dominating candidate passes
    assert stable_frontier_host(
        [{0: 5}, {0: 4}], [{0: 4}, {}]) == {0: 4}


def test_mint_appends_audit_ledger():
    ft = FakeTime()
    tr = StabilityTracker(StubNode({0: 5}), ["a"], clock=ft.now)
    assert tr.mint(step=1) == {}  # stalled: no summary yet
    assert tr.ledger == []        # empty mints leave no ledger row
    tr.note("a", {0: 4}, {})
    f = tr.mint(step=2)
    assert f == {0: 4}
    [rec] = tr.ledger
    assert rec["step"] == 2
    assert rec["frontier"] == {0: 4}
    assert rec["summaries"]["a"] == {0: 4}
    assert tr.last_frontier == {0: 4}


def test_lag_ops_counts_debt_above_frontier():
    ft = FakeTime()
    node = StubNode({0: 9, 1: 4})
    tr = StabilityTracker(node, ["a"], clock=ft.now)
    tr.note("a", {0: 5, 1: 4}, {})
    tr.mint()
    # local holds (9+1)+(4+1)=15 ops, frontier covers (5+1)+(4+1)=11
    assert tr.lag_ops() == 4


def test_summary_header_roundtrip():
    raw = encode_summary(3, {0: 5, 7: 2}, {0: 1})
    d = decode_summary(raw)
    assert d == {"rid": 3, "vv": {0: 5, 7: 2}, "frontier": {0: 1}}
    assert decode_summary(None) is None
    assert decode_summary("not json{") is None
    assert decode_summary('{"vv":{}}') is None  # missing rid


# ------------------------------------------------------------------ session


def test_token_mint_and_join_laws():
    t = mint_token([(0, 3), (0, 7), (2, 1)])
    assert t == {0: 7, 2: 1}
    a, b = {0: 5, 1: 2}, {0: 3, 2: 9}
    j = token_join(a, b)
    assert j == {0: 5, 1: 2, 2: 9}
    assert token_join(b, a) == j            # commutative
    assert token_join(j, j) == j            # idempotent
    assert vv_dominates(j, a) and vv_dominates(j, b)  # lub


def test_vv_dominance():
    assert vv_dominates({0: 5, 1: 2}, {0: 5})
    assert not vv_dominates({0: 4}, {0: 5})
    assert not vv_dominates({}, {0: 0})
    assert vv_dominates({}, {})


def test_token_header_roundtrip():
    t = {0: 7, 3: 2}
    assert decode_token(encode_token(t)) == t
    assert decode_token(None) is None
    assert decode_token("garbage{") is None
    assert decode_token('[1,2]') is None  # JSON but not an object


def test_wait_for_dominance_times_out_on_fake_clock():
    ft = FakeTime()
    node = StubNode({0: 2})
    ok = wait_for_dominance(node, {0: 5}, timeout=0.5, poll=0.1,
                            clock=ft.now, sleep=ft.sleep)
    assert not ok
    assert ft.t >= 0.5          # slept exactly up to the deadline
    assert ft.sleeps == 5


def test_wait_for_dominance_proxy_fills_gap():
    ft = FakeTime()
    node = StubNode({0: 2})

    def proxy():
        node.vv[0] = 9  # the pulled delta lands

    ok = wait_for_dominance(node, {0: 5}, timeout=0.5, poll=0.1,
                            clock=ft.now, sleep=ft.sleep, proxy=proxy)
    assert ok
    assert ft.sleeps == 0  # proxied on the first round, never slept


def test_session_read_your_writes_via_proxy():
    a, b = mk_node(0), mk_node(1)
    idents = a.add_commands([{"k": "v1"}])
    token = mint_token(idents)
    ft = FakeTime()
    plane = mk_plane(b, [FakePeer(a, "a")], ft)
    # b has never gossiped with a; the session read must proxy-pull
    assert plane.read("k", level="session", token=token) == "v1"
    assert b.metrics._counts.get("reads_session") == 1


def test_session_token_timeout_503():
    a, b = mk_node(0), mk_node(1)
    token = mint_token(a.add_commands([{"k": "v1"}]))
    ft = FakeTime()
    plane = mk_plane(b, [], ft)  # nobody to proxy from
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.read("k", level="session", token=token)
    assert ei.value.reason == "token_timeout"
    assert ei.value.level == "session"
    [rec] = b.events.find(event="consistency_unavailable")
    assert rec["reason"] == "token_timeout"
    assert b.metrics._counts.get("consistency_unavailable") == 1


def test_read_your_writes_through_ingest_lane():
    # the real ticket path: ingest front door mints the ident the
    # session token is built from (http_shim POST /data does exactly this)
    a, b = mk_node(0), mk_node(1)
    door = IngestFrontDoor(a, max_batch=4, flush_deadline_s=0.001)
    ident = door.admit_kv({"k": "from-lane"}, timeout=5.0)
    assert ident is not None
    token = mint_token([ident])
    ft = FakeTime()
    plane = mk_plane(b, [FakePeer(a, "a")], ft)
    assert plane.read("k", level="session", token=token) == "from-lane"


def test_session_read_requires_token():
    ft = FakeTime()
    plane = mk_plane(mk_node(0), [], ft)
    with pytest.raises(ValueError):
        plane.read("k", level="session")


def test_unknown_level_rejected():
    ft = FakeTime()
    plane = mk_plane(mk_node(0), [], ft)
    with pytest.raises(ValueError):
        plane.read("k", level="strong")


# ------------------------------------------------------------- linearizable


def test_eventual_read_is_local_and_cheap():
    n = mk_node(0)
    n.add_commands([{"k": "v"}])
    ft = FakeTime()
    peer = FakePeer(mk_node(1), "p")
    plane = mk_plane(n, [peer], ft)
    assert plane.read("k") == "v"
    assert plane.read("missing") is None  # absent key is a valid answer
    assert peer.vv_calls == 0             # no quorum round paid


def test_eventual_read_on_dead_node_503s():
    n = mk_node(0)
    n.set_alive(False)
    ft = FakeTime()
    plane = mk_plane(n, [], ft)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.read("k")
    assert ei.value.reason == "node_down"


def test_linearizable_read_catches_up_to_quorum():
    a, b, c = mk_node(0), mk_node(1), mk_node(2)
    a.add_commands([{"k": "newest"}])
    ft = FakeTime()
    # b serves the read; a holds the op; c is behind like b
    plane = mk_plane(b, [FakePeer(a, "a"), FakePeer(c, "c")], ft)
    assert plane.read("k", level="linearizable") == "newest"
    assert b.metrics._counts.get("reads_linearizable") == 1
    h = b.metrics.registry.histogram("strong_read_quorum_seconds")
    assert h is not None and h.count == 1


def test_linearizable_quorum_loss_503_never_stale():
    a, b, c = mk_node(0), mk_node(1), mk_node(2)
    a.add_commands([{"k": "unreachable"}])
    pa, pc = FakePeer(a, "a"), FakePeer(c, "c")
    pa.down = pc.down = True
    ft = FakeTime()
    plane = mk_plane(b, [pa, pc], ft)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.read("k", level="linearizable")
    assert ei.value.reason == "quorum_lost"
    assert ei.value.acks == 1 and ei.value.quorum == 2
    assert not ei.value.indeterminate
    [rec] = b.events.find(event="consistency_unavailable")
    assert (rec["reason"], rec["acks"], rec["quorum"]) == ("quorum_lost", 1, 2)


def test_open_breaker_counts_as_missing_ack():
    a, b, c = mk_node(0), mk_node(1), mk_node(2)
    pa, pc = FakePeer(a, "a"), FakePeer(c, "c")
    pa.backed = pc.backed = True  # OPEN breakers: skipped, not timed out
    ft = FakeTime()
    plane = mk_plane(b, [pa, pc], ft)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.read("k", level="linearizable")
    assert ei.value.reason == "quorum_lost"
    assert pa.vv_calls == 0 and pc.vv_calls == 0  # no paid timeouts


def test_linearizable_catchup_timeout():
    a, b = mk_node(0), mk_node(1)
    a.add_commands([{"k": "v"}])
    pa = FakePeer(a, "a")
    pa.serve_deltas = False  # acks the vv probe but never serves the delta
    ft = FakeTime()
    plane = mk_plane(b, [pa], ft, strong_timeout=0.1, poll=0.02)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.read("k", level="linearizable")
    assert ei.value.reason == "catchup_timeout"
    assert ft.t >= 0.1  # burned the whole (fake) deadline, then failed loud


def test_quorum_override_self_sufficient():
    n = mk_node(0)
    n.add_commands([{"k": "v"}])
    ft = FakeTime()
    plane = mk_plane(n, [], ft, quorum=1)  # explicit quorum of one
    assert plane.read("k", level="linearizable") == "v"


# -------------------------------------------------------------------- cas


def test_cas_matrix():
    a, b = mk_node(0), mk_node(1)
    ft = FakeTime()
    plane = mk_plane(a, [FakePeer(b, "b")], ft)
    # absent + expect None -> applied; returned token covers the write
    token = plane.cas("k", None, "v1")
    assert vv_dominates(a.version_vector(), token)
    assert plane.read("k") == "v1"
    # present + expect None -> conflict carrying the actual value
    with pytest.raises(CasConflict) as ei:
        plane.cas("k", None, "v2")
    assert ei.value.actual == "v1"
    # wrong expectation -> conflict
    with pytest.raises(CasConflict):
        plane.cas("k", "nope", "v2")
    # matching expectation -> applied
    plane.cas("k", "v1", "v2")
    assert plane.read("k") == "v2"
    assert b.get_state().get("k") == "v2"  # write quorum really pushed
    assert a.metrics._counts.get("cas_applied") == 2
    assert a.metrics._counts.get("cas_conflicts") == 2


def test_cas_sees_remote_write_before_deciding():
    # the linearizable read half of CAS: b's newer value must be pulled
    # in before the expectation is evaluated, even though a never gossiped
    a, b = mk_node(0), mk_node(1)
    b.add_commands([{"k": "remote"}])
    ft = FakeTime()
    plane = mk_plane(a, [FakePeer(b, "b")], ft)
    with pytest.raises(CasConflict) as ei:
        plane.cas("k", None, "v")
    assert ei.value.actual == "remote"


def test_cas_quorum_lost_before_mint_is_clean():
    a, b = mk_node(0), mk_node(1)
    pb = FakePeer(b, "b")
    pb.down = True
    ft = FakeTime()
    plane = mk_plane(a, [pb], ft)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.cas("k", None, "v1")
    assert ei.value.reason == "quorum_lost"
    assert not ei.value.indeterminate  # nothing was minted
    assert a.get_state().get("k") is None


def test_cas_indeterminate_when_write_quorum_lost():
    a, b = mk_node(0), mk_node(1)
    pb = FakePeer(b, "b")
    pb.accept_push = False  # read quorum fine; synchronous push dropped
    ft = FakeTime()
    plane = mk_plane(a, [pb], ft)
    with pytest.raises(ConsistencyUnavailable) as ei:
        plane.cas("k", None, "v1")
    assert ei.value.reason == "write_quorum_lost"
    assert ei.value.indeterminate           # minted locally, outcome unknown
    assert a.get_state().get("k") == "v1"   # the op exists and will gossip
    [rec] = a.events.find(event="consistency_unavailable")
    assert rec["indeterminate"] is True


def test_cas_proxy_quarantines_corrupt_payload():
    # a corrupted proxied payload is skipped + logged with the SAME event
    # the pull loop uses, so the nemesis corruption audit stays 1:1
    a, b = mk_node(0), mk_node(1)
    b.add_commands([{"k": "v"}])

    class CorruptPeer(FakePeer):
        def gossip_payload(self, since=None):
            p = dict(super().gossip_payload(since=since) or {})
            p["nemesis:corrupt:key"] = {"Key": "x", "Value": "y"}
            return p

    ft = FakeTime()
    plane = mk_plane(a, [CorruptPeer(b, "b")], ft, strong_timeout=0.1)
    with pytest.raises(ConsistencyUnavailable):
        plane.read("k", level="linearizable")
    assert a.events.find(event="payload_quarantine")
    assert a.metrics._counts.get("consistency_proxy_quarantine", 0) >= 1
