"""Host-runtime delta gossip + compaction tests (crdt_tpu.api).

The device-side contracts live in tests/test_compactlog.py; these check the
wire/runtime layer: version-vector payload filtering, summary adoption on
revival, command-map pruning, checkpoint round-trips, and that a compacting
cluster stays observably identical to a reference-faithful (never-pruning)
one — the capability the reference lacks (its log and gossip payload grow
without bound, /root/reference/main.go:75, main.go:159).
"""
import numpy as np
import pytest

from crdt_tpu.api.cluster import LocalCluster
from crdt_tpu.api.node import FRONTIER_KEY, SUMMARY_KEY, ReplicaNode
from crdt_tpu.models import oplog
from crdt_tpu.utils.clock import HostClock
from crdt_tpu.utils.config import ClusterConfig


def _mk_cluster(**kw):
    kw.setdefault("n_replicas", 4)
    kw.setdefault("log_capacity", 64)
    return LocalCluster(ClusterConfig(**kw))


def _drive(cluster, writes, seed=0):
    rng = np.random.default_rng(seed)
    for i, (key, val) in enumerate(writes):
        rid = int(rng.integers(0, len(cluster.nodes)))
        cluster.nodes[rid].add_command({key: val}, ts=i * 10)
    return cluster


WRITES = [
    ("a", "5"), ("b", "-20"), ("a", "7"), ("c", "hello"),
    ("b", "3"), ("c", "world"), ("a", "-1"), ("d", "007"),
]


def _converge(cluster, max_ticks=60):
    for _ in range(max_ticks):
        cluster.tick()
        if cluster.converged():
            return True
    return cluster.converged()


def test_delta_payload_excludes_known_ops():
    c = _mk_cluster()
    _drive(c, WRITES)
    a, b = c.nodes[0], c.nodes[1]
    full = b.gossip_payload()
    delta = b.gossip_payload(since=b.version_vector())
    assert delta == {}  # b needs nothing from itself
    # a pull with a's vv carries exactly b's ops that a is missing
    d = b.gossip_payload(since=a.version_vector())
    assert set(d) <= set(full)
    a_known = set(a._commands)
    for k in full:
        ts, rid, seq = map(int, k.split(":"))
        missing = (ts - a.clock.epoch_ms, rid, seq) not in a_known
        assert (k in d) == missing


def test_delta_and_full_gossip_converge_identically():
    ca = _drive(_mk_cluster(delta_gossip=True), WRITES)
    cb = _drive(_mk_cluster(delta_gossip=False), WRITES)
    assert _converge(ca) and _converge(cb)
    assert ca.nodes[0].get_state() == cb.nodes[0].get_state()


def test_compaction_preserves_state_and_prunes():
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    want = [n.get_state() for n in c.nodes]
    sizes_before = [len(n._commands) for n in c.nodes]
    frontier = c.compact()
    assert frontier  # everything was stable post-convergence
    for n, w, sz in zip(c.nodes, want, sizes_before):
        assert n.get_state() == w
        assert len(n._commands) < sz
        assert len(n._commands) == 0  # fully stable -> fully folded
        assert int(oplog.size(n.log)) == 0
        assert n._summary


def test_compacting_cluster_matches_reference_faithful_one():
    """End-to-end: periodic barriers + delta gossip + continued writes give
    the same observable states as the never-pruning configuration."""
    ca = _mk_cluster(compact_every=3)
    cb = _mk_cluster(compact_every=0, delta_gossip=False)
    for cl in (ca, cb):
        _drive(cl, WRITES)
        for _ in range(4):
            cl.tick()
        _drive(cl, [("e", "100"), ("a", "2"), ("f", "xyz")], seed=1)
        assert _converge(cl)
    assert ca.nodes[0].get_state() == cb.nodes[0].get_state()
    # and compaction actually bounded the command maps
    ca.compact()
    assert all(len(n._commands) == 0 for n in ca.nodes)
    assert all(len(n._commands) > 0 for n in cb.nodes)


def test_gossip_after_compaction_ships_summary_not_ops():
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    c.compact()
    fresh = ReplicaNode(rid=99, capacity=64, clock=HostClock())
    payload = c.nodes[0].gossip_payload(since=fresh.version_vector())
    assert FRONTIER_KEY in payload and SUMMARY_KEY in payload
    fresh.receive(payload)
    assert fresh.get_state() == c.nodes[0].get_state()
    # a requester that already covers the frontier still gets the frontier
    # (it piggybacks on every payload so caught-up peers prune eagerly at
    # adoption time) but NOT the heavyweight summary sections
    p2 = c.nodes[0].gossip_payload(since=c.nodes[1].version_vector())
    assert FRONTIER_KEY in p2 and SUMMARY_KEY not in p2


def test_frontier_piggyback_prunes_caught_up_peer():
    """Eager pruning below the stable frontier: a caught-up peer adopts a
    piggybacked frontier WITHOUT summary sections by folding its own raw
    ops locally, dropping its _commands/_by_writer slices at adoption time
    — it never has to call compact() itself."""
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    a, b = c.nodes[0], c.nodes[1]
    frontier = {r: s for r, s in a.version_vector().items()}
    a.compact(frontier)
    assert b._frontier == {} and len(b._commands) == len(WRITES)
    # b is fully caught up, so a's delta payload to b carries the frontier
    # but NO summary — and zero raw ops
    p = a.gossip_payload(since=b.version_vector())
    assert FRONTIER_KEY in p and SUMMARY_KEY not in p
    before = dict(b.metrics._counts)  # cluster nodes share one registry
    absorbed = b.receive(p)
    assert absorbed == 1  # the adoption counts, no raw ops rode along
    assert b._frontier == a._frontier
    # the local fold is bit-identical to a's explicit one
    assert b._summary == a._summary
    assert b.get_state() == a.get_state()
    # and the indexes actually shrank: everything under the frontier is gone
    assert len(b._commands) == 0
    assert all(len(lst) == 0 for lst in b._by_writer.values())
    after = b.metrics._counts
    assert after.get("frontier_adoptions", 0) - before.get("frontier_adoptions", 0) == 1
    assert after.get("compactions", 0) == before.get("compactions", 0)


def test_dead_node_misses_barrier_then_adopts_summary():
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    dead = c.nodes[2]
    dead.set_alive(False)
    # new writes + a barrier while node 2 is down
    c.nodes[0].add_command({"z": "41"}, ts=10_000)
    assert _converge(c)
    c.compact()
    assert dead._frontier == {}  # missed the barrier
    dead.set_alive(True)
    assert _converge(c)
    assert dead.get_state() == c.nodes[0].get_state()
    assert dead._frontier == c.nodes[0]._frontier


def test_refolded_ops_are_not_reingested():
    """A full (legacy, since=None) payload re-delivering folded ops must not
    double-count them against the summary."""
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    want = c.nodes[0].get_state()
    legacy = c.nodes[1].gossip_payload()  # full dump, pre-compaction
    c.compact()
    c.nodes[0].receive(legacy)
    assert c.nodes[0].get_state() == want


def test_incomparable_frontiers_fail_loudly():
    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    c.compact()
    n = c.nodes[0]
    bad_frontier = {str(r): s for r, s in n._frontier.items()}
    some = next(iter(n._frontier))
    bad_frontier[str(some)] = n._frontier[some] - 1
    bad_frontier["97"] = 5  # ahead on a writer we never folded
    with pytest.raises(ValueError, match="incomparable"):
        n.receive({FRONTIER_KEY: bad_frontier, SUMMARY_KEY: {}})


def test_checkpoint_roundtrips_compaction_state():
    from crdt_tpu.utils import checkpoint

    c = _drive(_mk_cluster(), WRITES)
    assert _converge(c)
    c.compact()
    node = c.nodes[1]
    want = node.get_state()
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        checkpoint.save_node(d, node)
        clone = ReplicaNode(rid=node.rid, capacity=64)
        checkpoint.restore_node(d, clone)
        assert clone._frontier == node._frontier
        assert clone._summary == node._summary
        assert clone.get_state() == want


def test_barrier_skipped_when_frontier_holders_dead():
    """Host chain rule (the wedge scenario): node2 dead through barrier 1;
    then nodes 0,1 die and node 2 (with fresh writes) is the only one up —
    compact() must skip rather than mint an incomparable frontier, and the
    cluster must fully recover after revival."""
    c = _mk_cluster(n_replicas=3)
    c.nodes[2].set_alive(False)
    c.nodes[0].add_command({"a": "5"}, ts=10)
    c.nodes[1].add_command({"b": "7"}, ts=20)
    assert _converge(c)
    f1 = c.compact()
    assert f1  # barrier 1 succeeded (among nodes 0,1)

    c.nodes[0].set_alive(False)
    c.nodes[1].set_alive(False)
    c.nodes[2].set_alive(True)
    c.nodes[2].add_command({"z": "1"}, ts=30)
    assert c.compact() == {}  # skipped: nodes 0,1 hold the only fold copies
    assert c.nodes[2]._frontier == {}

    for n in c.nodes:
        n.set_alive(True)
    assert _converge(c)  # revival merges stay on the chain -> no ValueError
    states = [n.get_state() for n in c.nodes]
    assert states[0] == states[1] == states[2]
    assert states[0]["a"] == "5" and states[0]["z"] == "1"
    f2 = c.compact()  # barrier resumes once the fold has spread
    assert all(f2.get(r, -1) >= s for r, s in f1.items())
    assert all(n.get_state() == states[0] for n in c.nodes)
