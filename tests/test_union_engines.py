"""Randomized differential suite over the three set-union engines.

The parity contract (crdt_tpu.ops.union_engine): every engine takes the
same canonical sorted-columnar operands and returns bit-identical
(keys, vals, n_unique) to the proven sort path — including under out_size
truncation.  This suite drives all three paths over identical operand
traces (duplicate-heavy, sentinel-edge, capacity-boundary, empty) plus a
host python-set oracle, and pins the auto-dispatch heuristic and the
union_path observability counters.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from crdt_tpu.models import gset, orset
from crdt_tpu.ops import pack, pallas_union, randstate as rs, union_engine as ue
from crdt_tpu.utils.constants import SENTINEL_PY

C, L = 64, 128
KEY_BITS = 12
UNIVERSE = 1 << KEY_BITS


def _mk(rng, fill, space=UNIVERSE, exact=False):
    """Random sorted-columnar operand planes: per-lane sorted unique keys
    with SENTINEL tail, 0/1 tombstone values."""
    ks = np.full((C, L), SENTINEL_PY, np.int32)
    vs = np.zeros((C, L), np.int32)
    for lane in range(L):
        n = fill if exact else int(rng.integers(0, fill + 1))
        keys = np.sort(rng.choice(space, size=n, replace=False)).astype(np.int32)
        ks[:n, lane] = keys
        vs[:n, lane] = rng.integers(0, 2, size=n)
    return jnp.asarray(ks), jnp.asarray(vs)


def _overlapping(rng, fill):
    """Duplicate-heavy pair: B replays most of A's keys with flipped
    tombstones, so the OR-combine path is exercised on nearly every row."""
    ka, va = _mk(rng, fill)
    kb = np.asarray(ka).copy()
    vb = 1 - np.asarray(va)
    # sprinkle a few fresh keys into B's padding
    for lane in range(0, L, 7):
        n = int(np.sum(kb[:, lane] != SENTINEL_PY))
        extra = min(3, C - n)
        fresh = rng.choice(UNIVERSE, size=extra, replace=False).astype(np.int32)
        col = np.concatenate([kb[:n, lane], fresh])
        order = np.argsort(col, kind="stable")
        kb[: n + extra, lane] = col[order]
        vb[: n + extra, lane] = np.concatenate(
            [vb[:n, lane], rng.integers(0, 2, size=extra)])[order]
    return (ka, va), (jnp.asarray(kb), jnp.asarray(np.where(
        kb == SENTINEL_PY, 0, vb).astype(np.int32)))


def _oracle(ka, va, kb, vb, out_size):
    """Host python-dict union: OR on duplicate keys, sorted, truncated."""
    keys_out = np.full((out_size, L), SENTINEL_PY, np.int32)
    vals_out = np.zeros((out_size, L), np.int32)
    n_out = np.zeros((L,), np.int32)
    ka, va, kb, vb = map(np.asarray, (ka, va, kb, vb))
    for lane in range(L):
        d = {}
        for k, v in zip(ka[:, lane], va[:, lane]):
            if k != SENTINEL_PY:
                d[int(k)] = d.get(int(k), 0) | int(v)
        for k, v in zip(kb[:, lane], vb[:, lane]):
            if k != SENTINEL_PY:
                d[int(k)] = d.get(int(k), 0) | int(v)
        items = sorted(d.items())[:out_size]
        for i, (k, v) in enumerate(items):
            keys_out[i, lane] = k
            vals_out[i, lane] = v
        n_out[lane] = len(d)
    return keys_out, vals_out, n_out


def _run_all(ka, va, kb, vb, out_size):
    sort = pallas_union.sorted_union_columnar(
        ka, va, kb, vb, out_size=out_size, interpret=True)
    bucket = ue.engine_bucket(ka, va, kb, vb, out_size,
                              use_kernel=False, interpret=True,
                              key_bits=KEY_BITS)
    bucket_k = ue.engine_bucket(ka, va, kb, vb, out_size,
                                use_kernel=True, interpret=True,
                                key_bits=KEY_BITS)
    bitmap = ue.engine_bitmap(ka, va, kb, vb, out_size, universe=UNIVERSE)
    return {"sort": sort, "bucket": bucket, "bucket_kernel": bucket_k,
            "bitmap": bitmap}


def _assert_identical(results, oracle=None):
    ref = results["sort"]
    for name, out in results.items():
        for i, part in enumerate(("keys", "vals", "count")):
            np.testing.assert_array_equal(
                np.asarray(ref[i]), np.asarray(out[i]),
                err_msg=f"engine {name} diverges from sort on {part}")
    if oracle is not None:
        for i, part in enumerate(("keys", "vals", "count")):
            np.testing.assert_array_equal(
                oracle[i], np.asarray(ref[i]),
                err_msg=f"sort path diverges from host oracle on {part}")


@pytest.mark.parametrize("fill", [0, 3, 20, 40])
def test_engines_bit_identical_random(fill):
    rng = np.random.default_rng(fill)
    ka, va = _mk(rng, fill)
    kb, vb = _mk(rng, fill)
    _assert_identical(_run_all(ka, va, kb, vb, C),
                      _oracle(ka, va, kb, vb, C))


def test_engines_bit_identical_duplicate_heavy():
    rng = np.random.default_rng(7)
    (ka, va), (kb, vb) = _overlapping(rng, 30)
    _assert_identical(_run_all(ka, va, kb, vb, C),
                      _oracle(ka, va, kb, vb, C))


def test_engines_bit_identical_empty_operands():
    rng = np.random.default_rng(8)
    ka, va = _mk(rng, 10)
    ke, ve = _mk(rng, 0)  # all-SENTINEL
    for a, b in [((ka, va), (ke, ve)), ((ke, ve), (ka, va)),
                 ((ke, ve), (ke, ve))]:
        _assert_identical(_run_all(a[0], a[1], b[0], b[1], C))


def test_engines_bit_identical_sentinel_edge():
    """Largest real key (UNIVERSE - 1, top bucket, top bitmap bit) and
    key 0 both present — the boundary rows of every layout."""
    rng = np.random.default_rng(9)
    ks = np.full((C, L), SENTINEL_PY, np.int32)
    vs = np.zeros((C, L), np.int32)
    for lane in range(L):
        mids = rng.choice(np.arange(1, UNIVERSE - 1), size=18, replace=False)
        keys = np.sort(np.concatenate(
            [[0, UNIVERSE - 1], mids])).astype(np.int32)
        ks[:20, lane] = keys
        vs[:20, lane] = rng.integers(0, 2, size=20)
    ka, va = jnp.asarray(ks), jnp.asarray(vs)
    kb, vb = _mk(rng, 20)
    _assert_identical(_run_all(ka, va, kb, vb, C))


def test_bitmap_universe_smaller_than_out_size_pads():
    """universe < out_size: the bitmap engine must still return out_size
    planes (SENTINEL keys / zero vals past the universe), bit-identical
    to the sort path — the shape regression behind auto-dispatched small
    dense universes."""
    rng = np.random.default_rng(14)
    small = 32  # bitmap_words(32) = 1 <= C, so auto picks bitmap
    ka, va = _mk(rng, 10, space=small)
    kb, vb = _mk(rng, 10, space=small)
    sort = pallas_union.sorted_union_columnar(
        ka, va, kb, vb, out_size=C, interpret=True)
    bitmap = ue.engine_bitmap(ka, va, kb, vb, C, universe=small)
    assert bitmap[0].shape == (C, L) and bitmap[1].shape == (C, L)
    _assert_identical({"sort": sort, "bitmap": bitmap},
                      _oracle(ka, va, kb, vb, C))
    keys, vals, _, path = ue.dispatch_union(
        ka, va, kb, vb, C, engine="auto", universe=small, interpret=True)
    assert path == "bitmap" and keys.shape == (C, L)
    np.testing.assert_array_equal(np.asarray(sort[0]), np.asarray(keys))
    np.testing.assert_array_equal(np.asarray(sort[1]), np.asarray(vals))


def test_engines_bit_identical_capacity_boundary():
    """Both operands full: the union truncates (all engines must keep the
    SMALLEST out_size keys and report the pre-truncation count)."""
    rng = np.random.default_rng(10)
    ka, va = _mk(rng, C, exact=True)
    kb, vb = _mk(rng, C, exact=True)
    results = _run_all(ka, va, kb, vb, C)
    _assert_identical(results, _oracle(ka, va, kb, vb, C))
    assert int(np.max(np.asarray(results["sort"][2]))) > C  # truly truncated


# ---- layout conversions -----------------------------------------------------


def test_bucketed_roundtrip():
    rng = np.random.default_rng(11)
    ka, va = _mk(rng, 12)
    kb2, vb2, dropped = ue.sorted_to_bucketed(ka, va, 8, KEY_BITS)
    assert int(jnp.sum(dropped)) == 0
    k3, v3, n3 = ue.bucketed_to_sorted(kb2, vb2)
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(k3))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(v3))
    np.testing.assert_array_equal(
        np.sum(np.asarray(ka) != SENTINEL_PY, axis=0), np.asarray(n3))


def test_bitmap_roundtrip():
    rng = np.random.default_rng(12)
    ka, va = _mk(rng, 12)
    p, r = ue.sorted_to_bitmap(ka, va, UNIVERSE)
    k3, v3, n3 = ue.bitmap_to_sorted(p, r, C)
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(k3))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(v3))
    np.testing.assert_array_equal(
        np.sum(np.asarray(ka) != SENTINEL_PY, axis=0), np.asarray(n3))


def test_bitmap_top_bit_word_boundary():
    """Bit 31 of a word packs as a NEGATIVE int32 — OR/popcount/extract
    must still round-trip it."""
    ks = np.full((C, L), SENTINEL_PY, np.int32)
    ks[0, :] = 31   # bit 31 of word 0
    ks[1, :] = 63   # bit 31 of word 1
    vs = np.zeros((C, L), np.int32)
    vs[0, :] = 1
    ka, va = jnp.asarray(ks), jnp.asarray(vs)
    p, r = ue.sorted_to_bitmap(ka, va, 64)
    assert int(np.asarray(p)[0, 0]) < 0  # bit 31 set -> negative word
    k3, v3, n3 = ue.bitmap_to_sorted(p, r, C)
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(k3))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(v3))
    assert int(np.asarray(n3)[0]) == 2


# ---- dispatcher + observability ---------------------------------------------


def test_plan_dense_universe_goes_bitmap():
    # traffic-parity bound: ceil(U/32) words <= capacity rows
    plan = ue.plan_union(C, universe=32 * C)
    assert plan.path == "bitmap"
    assert ue.plan_union(C, universe=32 * C + 1).path != "bitmap"


def test_plan_sparse_goes_bucket_then_sort():
    assert ue.plan_union(1024).path == "bucket"
    # over the key-bit budget -> sort
    assert ue.plan_union(1024, key_bits=40).path == "sort"
    # below the bucketed minimum -> sort
    assert ue.plan_union(16).path == "sort"
    # non-power-of-two capacity -> sort
    assert ue.plan_union(96).path == "sort"
    # universe too wide for traffic parity -> not bitmap
    assert ue.plan_union(C, universe=33 * 32 * C).path != "bitmap"


def test_dispatch_records_union_path_tally():
    ue.reset_tallies()
    rng = np.random.default_rng(13)
    ka, va = _mk(rng, 5, space=1024)
    kb, vb = _mk(rng, 5, space=1024)
    _, _, _, p1 = ue.dispatch_union(ka, va, kb, vb, C, engine="auto",
                                    universe=1024, interpret=True)
    _, _, _, p2 = ue.dispatch_union(ka, va, kb, vb, C, engine="sort",
                                    interpret=True)
    assert p1 == "bitmap" and p2 == "sort"
    counts = ue.union_path_counts()
    assert counts["bitmap"] == 1 and counts["sort"] == 1


def test_sampler_converges_tally_into_registry_monotone():
    from crdt_tpu.obs import health
    from crdt_tpu.obs.registry import MetricsRegistry

    ue.reset_tallies()
    reg = MetricsRegistry()
    health.sample_union_paths(reg)
    # the series exists even before any join ran
    assert reg.counter_value("union_path", path="sort") == 0
    ue.record_union_path("bitmap", 3)
    health.sample_union_paths(reg)
    health.sample_union_paths(reg)  # idempotent: no double count
    assert reg.counter_value("union_path", path="bitmap") == 3
    ue.record_union_path("bitmap")
    health.sample_union_paths(reg)
    assert reg.counter_value("union_path", path="bitmap") == 4
    assert "crdt_union_path_total" in reg.render_prometheus()


def test_record_union_path_direct_registry():
    from crdt_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    ue.record_union_path("bucket", registry=reg)
    assert reg.counter_value("union_path", path="bucket") == 1


def test_direct_registry_record_not_double_counted_by_scrape():
    """A directly-recorded event lands in BOTH the registry and the
    process tally; the scrape-time sampler must not converge it again."""
    from crdt_tpu.obs import health
    from crdt_tpu.obs.registry import MetricsRegistry

    ue.reset_tallies()
    reg = MetricsRegistry()
    ue.record_union_path("bucket", registry=reg)
    health.sample_union_paths(reg)
    assert reg.counter_value("union_path", path="bucket") == 1
    # mixed traffic: one direct, one tally-only — scrape adds only the
    # tally-only delta
    ue.record_union_path("bucket")
    ue.record_union_path("bucket", registry=reg)
    health.sample_union_paths(reg)
    assert reg.counter_value("union_path", path="bucket") == 3


def test_bucket_overflow_fallback_tallies_served_path():
    """One operand packs > Wb keys into a single bucket, so engine_bucket
    serves the sort path — and says so on the tally."""
    ue.reset_tallies()
    rng = np.random.default_rng(15)
    # default plan at C=64: 4 buckets of 16 rows over a 31-bit key space;
    # 20 keys < 4096 all land in bucket 0 -> conversion overflow
    ka, va = _mk(rng, 20, exact=True)
    kb, vb = _mk(rng, 20, exact=True)
    sort = pallas_union.sorted_union_columnar(
        ka, va, kb, vb, out_size=C, interpret=True)
    keys, vals, n, path = ue.dispatch_union(ka, va, kb, vb, C,
                                            engine="bucket", interpret=True)
    assert path == "bucket"
    assert ue.union_path_counts() == {"bucket": 1, "bucket_fallback_sort": 1}
    for ref, got in zip(sort, (keys, vals, n)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_dispatch_validates_pinned_engine():
    rng = np.random.default_rng(16)
    ka, va = _mk(rng, 5)
    kb, vb = _mk(rng, 5)
    with pytest.raises(ValueError, match="universe"):
        ue.dispatch_union(ka, va, kb, vb, C, engine="bitmap")
    with pytest.raises(KeyError, match="unknown union engine"):
        ue.dispatch_union(ka, va, kb, vb, C, engine="radix")
    # capacity 96: not a power of two -> descriptive refusal, not a
    # trace-time AssertionError inside bucket_shift
    k96 = jnp.full((96, 8), SENTINEL_PY, jnp.int32)
    v96 = jnp.zeros((96, 8), jnp.int32)
    with pytest.raises(ValueError, match="power-of-two"):
        ue.dispatch_union(k96, v96, k96, v96, 96, engine="bucket")
    # capacity 32: below the bucketed minimum
    k32 = jnp.full((32, 8), SENTINEL_PY, jnp.int32)
    v32 = jnp.zeros((32, 8), jnp.int32)
    with pytest.raises(ValueError, match="power-of-two"):
        ue.dispatch_union(k32, v32, k32, v32, 32, engine="bucket")


# ---- pack hardening + strict joins ------------------------------------------


def test_pack_tags_checked_raises_per_field():
    ok = np.array([1, 2], np.int32)
    with pytest.raises(ValueError, match="elem"):
        pack.pack_tags_checked(np.array([1 << 14], np.int32), ok[:1], ok[:1])
    with pytest.raises(ValueError, match="rid"):
        pack.pack_tags_checked(ok[:1], np.array([64], np.int32), ok[:1])
    with pytest.raises(ValueError, match="seq"):
        pack.pack_tags_checked(ok[:1], ok[:1], np.array([1 << 11], np.int32))
    with pytest.raises(ValueError, match="rid"):
        pack.pack_tags_checked(ok[:1], np.array([-1], np.int32), ok[:1])
    # valid mask exempts padding rows
    got = pack.pack_tags_checked(
        np.array([3, 1 << 20], np.int32), np.array([2, 99], np.int32),
        np.array([7, -5], np.int32), valid=np.array([True, False]))
    assert int(np.asarray(got)[0]) == int(np.asarray(
        pack.pack_tags(jnp.asarray([3]), jnp.asarray([2]),
                       jnp.asarray([7])))[0])


def test_stack_to_columnar_rejects_over_budget_tags():
    s = orset.empty(8)
    s = orset.add(s, 5, 1, (1 << 11) + 3)  # seq over budget
    with pytest.raises(ValueError, match="seq"):
        orset.stack_to_columnar([s])


def test_orset_join_strict_raises_and_tallies():
    ue.reset_tallies()
    a = orset.empty(2)
    a = orset.add(a, 1, 0, 0)
    a = orset.add(a, 2, 0, 1)
    b = orset.empty(2)
    b = orset.add(b, 3, 1, 0)
    with pytest.raises(ue.UnionOverflow):
        orset.join_strict(a, b)
    assert ue.truncation_count() == 1
    # non-overflowing joins pass through untouched
    got = orset.join_strict(a, a)
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(got.elem))
    assert ue.truncation_count() == 1


def test_gset_join_strict_raises():
    a = gset.GSet(elem=jnp.asarray([1, 2], jnp.int32))
    b = gset.GSet(elem=jnp.asarray([3, 4], jnp.int32))
    with pytest.raises(ue.UnionOverflow):
        gset.g_join_strict(a, b)
    got = gset.g_join_strict(a, a)
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(got.elem))


def test_gset_join_auto_bitmap_parity_and_tally():
    ue.reset_tallies()
    a = gset.GSet(elem=jnp.asarray([1, 5, 9, SENTINEL_PY], jnp.int32))
    b = gset.GSet(elem=jnp.asarray([2, 5, 30, SENTINEL_PY], jnp.int32))
    ref = gset.g_join(a, b)
    got = gset.g_join_auto(a, b, universe=64)
    np.testing.assert_array_equal(np.asarray(ref.elem), np.asarray(got.elem))
    assert ue.union_path_counts() == {"bitmap": 1}
    # no universe declared -> sort fallback, still recorded
    got2 = gset.g_join_auto(a, b)
    np.testing.assert_array_equal(np.asarray(ref.elem), np.asarray(got2.elem))
    assert ue.union_path_counts() == {"bitmap": 1, "sort": 1}


# ---- resident model layouts -------------------------------------------------


def test_orset_bitmap_join_matches_canonical():
    rng = np.random.default_rng(20)
    a = rs.rand_orset(rng)
    b = rs.rand_orset(rng)
    universe = 1 << 20  # covers the packed (6, 3, 50) tag space
    ja = orset.join(a, b)
    jb = orset.from_bitmap(
        orset.bitmap_join(orset.to_bitmap(a, universe),
                          orset.to_bitmap(b, universe)), a.capacity)
    for f in ("elem", "rid", "seq", "removed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ja, f)), np.asarray(getattr(jb, f)),
            err_msg=f"bitmap-resident join diverges on {f}")


def test_orset_bucketed_join_matches_canonical():
    rng = np.random.default_rng(21)
    a = rs.rand_orset(rng)
    b = rs.rand_orset(rng)
    ja = orset.join(a, b)
    jb = orset.from_bucketed(
        orset.bucketed_join(orset.to_bucketed(a, 2, key_bits=20),
                            orset.to_bucketed(b, 2, key_bits=20)))
    for f in ("elem", "rid", "seq", "removed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ja, f)), np.asarray(getattr(jb, f)),
            err_msg=f"bucket-resident join diverges on {f}")


def test_to_bucketed_refuses_bucket_overflow():
    s = orset.empty(8)
    for i in range(5):
        s = orset.add(s, 0, 0, i)  # five tags, one elem -> one bucket
    with pytest.raises(ue.UnionOverflow):
        orset.to_bucketed(s, 4, key_bits=20)  # wb = 2 < 5


def test_columnar_join_engine_param_parity():
    rng = np.random.default_rng(22)
    sets_a = [rs.rand_orset(rng) for _ in range(4)]
    sets_b = [rs.rand_orset(rng) for _ in range(4)]
    pa, ra = orset.stack_to_columnar(sets_a)
    pb, rb = orset.stack_to_columnar(sets_b)
    ue.reset_tallies()
    ref = orset.columnar_join(pa, ra, pb, rb, out_size=16, interpret=True)
    got = orset.columnar_join(pa, ra, pb, rb, out_size=16, interpret=True,
                              engine="bitmap", universe=1 << 20)
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(ref[i]), np.asarray(got[i]))
    assert ue.union_path_counts() == {"sort": 1, "bitmap": 1}
