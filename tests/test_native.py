"""Native ingestion runtime: must agree exactly with the pure-Python path."""
import numpy as np
import pytest

from crdt_tpu import native
from crdt_tpu.utils import intern as py_intern

pytestmark = pytest.mark.skipif(
    not native.AVAILABLE, reason="native toolchain unavailable"
)


def test_interner_matches_python():
    ni = native.NativeInterner()
    pi = py_intern.Interner()
    words = ["a", "bb", "a", "", "ccc", "bb", "é", "a" * 1000] + [
        f"k{i}" for i in range(3000)  # force table growth
    ]
    for w in words:
        assert ni.intern(w) == pi.intern(w), w
    assert len(ni) == len(pi)
    for i in range(len(pi)):
        assert ni.lookup(i) == pi.lookup(i)


def test_parse_go_int_matches_python():
    cases = ["42", "-13", "+7", "007", "", " 1", "1 ", "1_0", "0x10", "1.5",
             "abc", "--1", "+", "2147483647", "2147483648", "-2147483648",
             "-2147483649", "0", "-0", "99999999999999999999"]
    for s in cases:
        assert native.parse_go_int(s) == py_intern.parse_go_int(s), s


def test_batch_packer_matches_encode_value():
    keys_n, vals_n = native.NativeInterner(), native.NativeInterner()
    keys_p, vals_p = py_intern.Interner(), py_intern.Interner()
    packer = native.OpBatchPacker(keys_n, vals_n)

    rows = [
        (10, 0, 0, "x", "5"),
        (11, 1, 0, "y", "hello"),
        (11, 1, 1, "x", "-20"),
        (12, 2, 0, "z", "007"),
    ]
    expect = {n: [] for n in ("ts", "rid", "seq", "key", "val", "payload", "is_num")}
    for ts, rid, seq, k, v in rows:
        packer.add(ts, rid, seq, k, v)
        val, payload, is_num = py_intern.encode_value(v, vals_p)
        expect["ts"].append(ts)
        expect["rid"].append(rid)
        expect["seq"].append(seq)
        expect["key"].append(keys_p.intern(k))
        expect["val"].append(val)
        expect["payload"].append(payload)
        expect["is_num"].append(is_num)

    got = packer.take()
    assert len(packer) == 0  # take() clears
    for name, exp in expect.items():
        assert got[name].tolist() == exp, name
    # interned tables agree with the python interner
    assert [keys_n.lookup(i) for i in range(len(keys_n))] == [
        keys_p.lookup(i) for i in range(len(keys_p))
    ]


def test_batch_feeds_oplog():
    from crdt_tpu.models import oplog

    keys, vals = native.NativeInterner(), native.NativeInterner()
    packer = native.OpBatchPacker(keys, vals)
    packer.add(1, 0, 0, "k", "5")
    packer.add(2, 0, 1, "k", "-3")
    log = oplog.from_ops(8, packer.take())
    kv = oplog.rebuild(log, n_keys=len(keys))
    assert oplog.materialize(kv, keys, vals) == {"k": "2"}


def test_contains_does_not_mutate():
    ni = native.NativeInterner()
    ni.intern("present")
    assert "present" in ni
    assert "absent" not in ni
    assert len(ni) == 1  # probing must not intern
