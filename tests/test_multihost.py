"""Multi-host helper tests (single-process degradation on the 8-device
virtual CPU mesh; real DCN spans are exercised by the same code because
mesh.py's collectives are ordinary XLA collectives)."""
import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.models import gcounter, oplog
from crdt_tpu.parallel import mesh as mesh_lib, multihost, swarm


def test_init_noop_without_cluster_env(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert multihost.init_from_env() is False


def test_global_mesh_covers_all_devices():
    m = multihost.global_mesh()
    assert m.devices.size == len(jax.devices()) == 8


def test_shard_host_local_and_converge():
    m = multihost.global_mesh()
    r = 16
    state = gcounter.GCounter(
        counts=np.arange(r * 4, dtype=np.int32).reshape(r, 4)
    )
    sharded = multihost.shard_host_local(state, m)
    assert sharded.counts.shape == (r, 4)
    s = swarm.make(sharded)
    step = mesh_lib.pmax_converge(m)
    out = step(s)
    want = np.asarray(state.counts).max(axis=0)
    got = np.asarray(out.state.counts)
    assert (got == want[None, :]).all()


def test_shard_host_local_generic_lattice():
    m = multihost.global_mesh()
    logs = [oplog.empty(32) for _ in range(8)]
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *logs)
    sharded = multihost.shard_host_local(state, m)
    s = swarm.make(sharded)
    step = mesh_lib.sharded_converge(
        m, join_batched=jax.vmap(oplog.merge), join_single=oplog.merge,
        neutral=oplog.empty(32),
    )
    out = step(s)
    assert int(jax.vmap(oplog.size)(out.state).sum()) == 0


def test_process_span_single():
    assert multihost.process_span() == (0, 1)
