"""Multi-host helper tests (single-process degradation on the 8-device
virtual CPU mesh; real DCN spans are exercised by the same code because
mesh.py's collectives are ordinary XLA collectives)."""
import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.models import gcounter, oplog
from crdt_tpu.parallel import mesh as mesh_lib, multihost, swarm


def test_init_noop_without_cluster_env(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert multihost.init_from_env() is False


def test_global_mesh_covers_all_devices():
    m = multihost.global_mesh()
    assert m.devices.size == len(jax.devices()) == 8


def test_shard_host_local_and_converge():
    m = multihost.global_mesh()
    r = 16
    state = gcounter.GCounter(
        counts=np.arange(r * 4, dtype=np.int32).reshape(r, 4)
    )
    sharded = multihost.shard_host_local(state, m)
    assert sharded.counts.shape == (r, 4)
    s = swarm.make(sharded)
    step = mesh_lib.pmax_converge(m)
    out = step(s)
    want = np.asarray(state.counts).max(axis=0)
    got = np.asarray(out.state.counts)
    assert (got == want[None, :]).all()


def test_shard_host_local_generic_lattice():
    m = multihost.global_mesh()
    logs = [oplog.empty(32) for _ in range(8)]
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *logs)
    sharded = multihost.shard_host_local(state, m)
    s = swarm.make(sharded)
    step = mesh_lib.sharded_converge(
        m, join_batched=jax.vmap(oplog.merge), join_single=oplog.merge,
        neutral=oplog.empty(32),
    )
    out = step(s)
    assert int(jax.vmap(oplog.size)(out.state).sum()) == 0


def test_process_span_single():
    assert multihost.process_span() == (0, 1)


def test_columnar_sharded_converge_on_global_mesh():
    """The fused-kernel sharded convergence runs over the multi-host
    global mesh unchanged (same shard_map + collectives; interpret-pallas
    on the CPU mesh, compiled Mosaic on TPU)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crdt_tpu.models import oplog_columnar as oc

    from tests.test_oplog_columnar import BITS, _op_pool, _random_batch

    m = multihost.global_mesh()
    rng = np.random.default_rng(0)
    c, r = 16, 16
    # lanes must hold subsets of a SHARED op pool: identical identities
    # carry identical payloads (the op-identity invariant every merge
    # path assumes)
    col = oc.stack(_random_batch(rng, r, c, _op_pool(rng, 12)), bits=BITS)
    sharded = jax.device_put(col, NamedSharding(m, P(None, "replica")))
    step = oc.sharded_converge(m, bits=col.bits)
    out, nu = step(sharded, jnp.ones((r,), bool))
    want = oc.converge(col, interpret=True)
    np.testing.assert_array_equal(np.asarray(out.hi), np.asarray(want.hi))
    np.testing.assert_array_equal(np.asarray(out.val), np.asarray(want.val))
    assert int(nu) <= c
