"""Go-compatible gossip emission (round-3, VERDICT round 2 missing #1):
with ``go_compat_gossip=True`` a crdt_tpu node's full-dump payload uses
the reference's bare integer-ms keys, so an ORIGINAL Go peer can pull from
it without its Atoi gossip loop dying (quirk §0.1.8) — interop becomes
bidirectional.  The Go side here is the quirk-faithful oracle shim
(crdt_tpu.oracle.shim: byte-level gin/treemap parity, tests/test_go_golden
pins it to main.go's bytes)."""
import json
import urllib.request

import pytest

from crdt_tpu.api.net import NodeHost, RemotePeer
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.oracle.shim import OracleHttpCluster
from crdt_tpu.utils.clock import ManualClock
from crdt_tpu.utils.config import ClusterConfig


def test_go_compat_payload_format_and_collision_policy():
    node = ReplicaNode(rid=3, go_compat_gossip=True)
    node.add_command({"x": "5"}, ts=100)
    node.add_command({"y": "7"}, ts=200)
    p = node.gossip_payload()  # full dump
    epoch = node.clock.epoch_ms
    assert set(p) == {str(100 + epoch), str(200 + epoch)}
    for k in p:
        int(k)  # every key must survive the Go peer's Atoi
    # same-ms ops collapse last-writer-per-ms (the reference's own
    # treemap-Put collision rule, quirk §0.1.2) — documented lossiness
    node.add_command({"x": "1"}, ts=300)
    node.add_command({"x": "2"}, ts=300)
    p = node.gossip_payload()
    assert p[str(300 + epoch)] == {"x": "2"}
    # delta payloads stay in the native collision-free format
    d = node.gossip_payload(since={})
    assert all(":" in k for k in d)


def test_go_compat_json_bytes_path():
    """gossip_payload_json (the HTTP serving path, native C++ emitter when
    built) must also emit the go format for full dumps."""
    node = ReplicaNode(rid=3, go_compat_gossip=True)
    node.add_command({"x": "5"}, ts=100)
    body = json.loads(node.gossip_payload_json().decode())
    assert list(body) == [str(100 + node.clock.epoch_ms)]


def test_go_compat_forbids_compaction():
    node = ReplicaNode(rid=3, go_compat_gossip=True)
    node.add_command({"x": "5"}, ts=100)
    with pytest.raises(ValueError, match="go-compat"):
        node.compact({3: 0})
    with pytest.raises(ValueError, match="go_compat_gossip"):
        NodeHost(rid=0, peers=[],
                 config=ClusterConfig(go_compat_gossip=True, compact_every=2,
                                      delta_gossip=True))
    with pytest.raises(ValueError, match="delta_gossip"):
        NodeHost(rid=0, peers=[],
                 config=ClusterConfig(go_compat_gossip=True,
                                      delta_gossip=False))


def test_bidirectional_mixed_fleet_converges():
    """The done-criterion: a quirk-faithful Go peer pulls from a go-compat
    framework daemon (its Atoi loop survives and learns the ops), AND the
    framework daemon pulls the Go peer's writes back — both directions
    over real HTTP sockets."""
    host = NodeHost(
        rid=0, peers=[], port=0,
        config=ClusterConfig(go_compat_gossip=True, delta_gossip=True),
    )
    host.start_server()
    # the Go peer's clock must mint keys inside the framework's int32
    # rebase window (the shim's ManualClock is absolute-ms)
    epoch = host.node.clock.epoch_ms
    shim = OracleHttpCluster(n=1, clock=ManualClock(start=epoch + 50_000))
    shim.start()
    try:
        # framework writes (distinct ms: the lossy collision rule is
        # test_go_compat_payload_format_and_collision_policy's subject)
        host.node.add_command({"a": "5"}, ts=100)
        host.node.add_command({"a": "-2"}, ts=200)
        host.node.add_command({"b": "hello"}, ts=300)

        # the Go peer writes FIRST: its merge has the reference's
        # tail-drop quirk (§0.1.3 — the two-pointer walk only adopts
        # remote entries older than its newest local entry, so an
        # empty-log peer adopts nothing), and its ManualClock key
        # (epoch+50000) is newer than every framework op
        res = shim.nodes[0].add_command({"c": "11"})
        assert res.status == 200

        # --- Go peer pulls from the framework daemon ---
        with urllib.request.urlopen(host.url + "/gossip") as res:
            wire = res.read().decode()
        shim.nodes[0].receive_wire(wire)  # Atoi path: must not die
        go_state = shim.nodes[0].get_state()
        assert go_state["a"] == "3" and go_state["b"] == "hello"
        # quirk §0.1.1 (faithfully reproduced): the Go peer's OWN write
        # vanishes from its local state after the merge — though it still
        # serves it to others
        assert "c" not in go_state

        # --- framework pulls the Go peer's write back ---
        ok = host.admin_pull(shim.urls[0])
        assert ok, "framework must absorb the Go peer's payload"
        state = host.node.get_state()
        assert state == {"a": "3", "b": "hello", "c": "11"}

        # --- second round trip: the Go peer keeps pulling (its loop is
        # alive — the whole point of the flag) ---
        host.node.add_command({"a": "1"}, ts=400)
        with urllib.request.urlopen(host.url + "/gossip") as res:
            shim.nodes[0].receive_wire(res.read().decode())
        go_state = shim.nodes[0].get_state()
        assert go_state["a"] == "4"
    finally:
        shim.stop()
        host.stop_server()


def test_native_format_kills_go_peer_loop_negative_control():
    """Without the flag, the native ts:rid:seq keys do kill a Go peer's
    pull (the shim's Atoi raises) — the behavior the flag exists to fix."""
    host = NodeHost(rid=0, peers=[], port=0, config=ClusterConfig())
    host.start_server()
    epoch = host.node.clock.epoch_ms
    shim = OracleHttpCluster(n=1, clock=ManualClock(start=epoch + 50_000))
    shim.start()
    try:
        host.node.add_command({"a": "5"}, ts=100)
        with urllib.request.urlopen(host.url + "/gossip") as res:
            wire = res.read().decode()
        with pytest.raises(ValueError):
            shim.nodes[0].receive_wire(wire)
    finally:
        shim.stop()
        host.stop_server()
