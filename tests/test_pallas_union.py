"""Pallas bitonic-merge union kernel tests (interpret mode on CPU; the real
Mosaic path runs in bench_orset.py on hardware).  Ground truth: python sets
and the generic XLA sorted_union."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crdt_tpu.ops import pack, pallas_union, sorted_union as su
from crdt_tpu.utils.constants import SENTINEL_PY


def _cols(rng, c, lanes, fill_max):
    """Per-lane sorted unique keys with SENTINEL padding + random vals."""
    keys = np.full((c, lanes), SENTINEL_PY, np.int32)
    vals = np.zeros((c, lanes), np.int32)
    for j in range(lanes):
        n = int(rng.integers(0, c + 1))
        ks = np.sort(rng.choice(fill_max, size=n, replace=False))
        keys[:n, j] = ks
        vals[:n, j] = rng.integers(0, 8, n)  # small flags, OR-combinable
    return jnp.asarray(keys), jnp.asarray(vals)


@pytest.mark.parametrize("c", [8, 64])
def test_columnar_union_matches_python_sets(c):
    rng = np.random.default_rng(c)
    lanes = 128
    ka, va = _cols(rng, c, lanes, fill_max=4 * c)
    kb, vb = _cols(rng, c, lanes, fill_max=4 * c)
    ko, vo, n = pallas_union.sorted_union_columnar(ka, va, kb, vb, interpret=True)
    ko, vo, n = np.asarray(ko), np.asarray(vo), np.asarray(n)

    for j in range(0, lanes, 17):  # spot-check lanes
        expect = {}
        for kk, vv in zip(np.asarray(kb)[:, j], np.asarray(vb)[:, j]):
            if kk != SENTINEL_PY:
                expect[int(kk)] = int(vv)
        for kk, vv in zip(np.asarray(ka)[:, j], np.asarray(va)[:, j]):
            if kk != SENTINEL_PY:
                expect[int(kk)] = expect.get(int(kk), 0) | int(vv)
        got_keys = [int(k) for k in ko[:, j] if k != SENTINEL_PY]
        got = {k: int(v) for k, v in zip(got_keys, vo[:, j])}
        assert got == expect, f"lane {j}"
        assert n[j] == len(expect)
        assert got_keys == sorted(got_keys)


def test_merge_kernel_is_sorted_even_with_dups():
    rng = np.random.default_rng(3)
    c, lanes = 32, 128
    ka, va = _cols(rng, c, lanes, fill_max=c)  # dense => many cross dups
    kb, vb = _cols(rng, c, lanes, fill_max=c)
    ko, _ = pallas_union.bitonic_merge_columnar(ka, va, kb, vb, interpret=True)
    ko = np.asarray(ko)
    assert (np.diff(ko, axis=0) >= 0).all(), "merged columns must be sorted"


def test_pack_roundtrip_and_order():
    rng = np.random.default_rng(0)
    elem = rng.integers(0, 1 << pack.ELEM_BITS, 1000)
    rid = rng.integers(0, 1 << pack.RID_BITS, 1000)
    seq = rng.integers(0, 1 << pack.SEQ_BITS, 1000)
    packed = np.asarray(pack.pack_tags(jnp.asarray(elem), jnp.asarray(rid), jnp.asarray(seq)))
    assert (packed >= 0).all()
    e2, r2, s2 = (np.asarray(x) for x in pack.unpack_tags(jnp.asarray(packed)))
    assert (e2 == elem).all() and (r2 == rid).all() and (s2 == seq).all()
    # numeric order == lexicographic order
    tuples = list(zip(elem, rid, seq))
    assert np.argsort(packed, kind="stable").tolist() == sorted(
        range(1000), key=lambda i: (tuples[i], i)
    )
    with pytest.raises(ValueError):
        pack.check_budget(1 << 20, 2, 2)


def test_columnar_union_agrees_with_generic_sorted_union():
    from crdt_tpu.ops import sorted_union as su

    rng = np.random.default_rng(9)
    c, lanes = 16, 128
    ka, va = _cols(rng, c, lanes, fill_max=64)
    kb, vb = _cols(rng, c, lanes, fill_max=64)
    ko, vo, _ = pallas_union.sorted_union_columnar(ka, va, kb, vb, interpret=True)

    for j in range(0, lanes, 31):
        keys, vals, _ = su.sorted_union(
            (ka[:, j],), va[:, j], (kb[:, j],), vb[:, j],
            combine=lambda x, y: x | y,
        )
        assert np.asarray(keys[0]).tolist() == np.asarray(ko[:, j]).tolist()
        assert np.asarray(vals).tolist() == np.asarray(vo[:, j]).tolist()


@pytest.mark.parametrize("c", [8, 64, 256])
def test_fused_matches_unfused(c):
    """The fused kernel (merge + dedupe + log-step compaction in VMEM) must
    be bit-identical to the two-pass variant on every field, across fill
    levels from empty to full."""
    rng = np.random.default_rng(100 + c)
    lanes = 128
    ka, va = _cols(rng, c, lanes, fill_max=4 * c)
    kb, vb = _cols(rng, c, lanes, fill_max=4 * c)
    for out in (c, 2 * c):
        fused = pallas_union.sorted_union_columnar_fused(
            ka, va, kb, vb, out_size=out, interpret=True)
        ref = pallas_union.sorted_union_columnar_unfused(
            ka, va, kb, vb, out_size=out, interpret=True)
        for f, r, name in zip(fused, ref, ("keys", "vals", "n_unique")):
            np.testing.assert_array_equal(
                np.asarray(f), np.asarray(r), err_msg=f"{name} out={out}")


def _lex2_cols(rng, c, lanes, hi_max, n_vals):
    """Per-lane sorted unique (hi, lo) pairs + n value planes."""
    hi = np.full((c, lanes), SENTINEL_PY, np.int32)
    lo = np.full((c, lanes), SENTINEL_PY, np.int32)
    vals = [np.zeros((c, lanes), np.int32) for _ in range(n_vals)]
    for j in range(lanes):
        n = int(rng.integers(0, c + 1))
        pairs = sorted(
            {(int(rng.integers(0, hi_max)), int(rng.integers(0, 4)))
             for _ in range(n)}
        )
        for r, (h, l) in enumerate(pairs):
            hi[r, j], lo[r, j] = h, l
            for v in vals:
                v[r, j] = h * 131 + l * 7 + 1  # value determined by key
    return jnp.asarray(hi), jnp.asarray(lo), [jnp.asarray(v) for v in vals]


@pytest.mark.parametrize("n_vals,out_mode", [(1, "cap"), (2, "full"), (3, "cap")])
def test_lex2_union_matches_generic(n_vals, out_mode):
    """The two-word lexicographic fused kernel must agree with the generic
    sorted_union on every plane, at both the capacity-bounded and the
    lossless (2C) output sizes, for any number of value planes.  Values are
    key-determined so the keep-first duplicate rule is well-posed."""
    from crdt_tpu.ops import sorted_union as su

    rng = np.random.default_rng(17 * n_vals)
    c, lanes = 16, 128
    ha, la, va = _lex2_cols(rng, c, lanes, hi_max=24, n_vals=n_vals)
    hb, lb, vb = _lex2_cols(rng, c, lanes, hi_max=24, n_vals=n_vals)
    out = c if out_mode == "cap" else 2 * c
    (ho, lo_), vo, nu = pallas_union.sorted_union_columnar_fused_lex2(
        (ha, la), tuple(va), (hb, lb), tuple(vb), out_size=out,
        interpret=True,
    )
    for j in range(0, lanes, 13):
        keys, vals, n = su.sorted_union(
            (ha[:, j], la[:, j]),
            {i: v[:, j] for i, v in enumerate(va)},
            (hb[:, j], lb[:, j]),
            {i: v[:, j] for i, v in enumerate(vb)},
            combine=su.keep_first,
            out_size=out,
        )
        np.testing.assert_array_equal(np.asarray(keys[0]), np.asarray(ho[:, j]))
        np.testing.assert_array_equal(np.asarray(keys[1]), np.asarray(lo_[:, j]))
        for i in range(n_vals):
            np.testing.assert_array_equal(
                np.asarray(vals[i]), np.asarray(vo[i][:, j]), err_msg=f"val {i}"
            )
        assert int(n) == int(nu[j])


def test_fused_empty_and_degenerate():
    c, lanes = 16, 128
    empty_k = jnp.full((c, lanes), SENTINEL_PY, jnp.int32)
    empty_v = jnp.zeros((c, lanes), jnp.int32)
    ko, vo, n = pallas_union.sorted_union_columnar_fused(
        empty_k, empty_v, empty_k, empty_v, interpret=True)
    assert (np.asarray(n) == 0).all()
    assert (np.asarray(ko) == SENTINEL_PY).all()
    # identical inputs: union == input (idempotence at the kernel level)
    rng = np.random.default_rng(1)
    ka, va = _cols(rng, c, lanes, fill_max=2 * c)
    ko, vo, n = pallas_union.sorted_union_columnar_fused(
        ka, va, ka, va, out_size=c, interpret=True)
    np.testing.assert_array_equal(np.asarray(ko), np.asarray(ka))
    np.testing.assert_array_equal(np.asarray(vo), np.asarray(va))


def _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=None):
    """Per-lane sorted unique n_keys-word rows + n value planes; plane
    ``or_plane`` (if given) is a random 0/1 monotone flag (tombstone-like),
    every other value plane is key-determined."""
    keys = [np.full((c, lanes), SENTINEL_PY, np.int32) for _ in range(n_keys)]
    vals = [np.zeros((c, lanes), np.int32) for _ in range(n_vals)]
    for j in range(lanes):
        n = int(rng.integers(0, c + 1))
        rows = sorted({
            tuple(int(rng.integers(0, 6)) for _ in range(n_keys))
            for _ in range(n)
        })
        for r, row in enumerate(rows):
            for k in range(n_keys):
                keys[k][r, j] = row[k]
            for i, v in enumerate(vals):
                if i == or_plane:
                    v[r, j] = int(rng.integers(0, 2))
                else:
                    v[r, j] = sum(row) * 31 + i + 1
    return ([jnp.asarray(k) for k in keys], [jnp.asarray(v) for v in vals])


@pytest.mark.parametrize("n_keys", [1, 3, 5])
def test_lexn_union_matches_generic(n_keys):
    """The N-word fused kernel at in-between key counts (1, 3, 5 — the
    shipped paths are 2 and 18), including the OR-combine-on-punch rule
    for a monotone flag plane whose duplicate copies DIFFER."""
    rng = np.random.default_rng(40 + n_keys)
    c, lanes, n_vals = 32, 128, 2
    ka, va = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    kb, vb = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    ko, vo, nu = pallas_union.sorted_union_columnar_fused_lexn(
        tuple(ka), tuple(va), tuple(kb), tuple(vb),
        out_size=c, interpret=True,
    )
    for j in range(0, lanes, 23):
        keys, vals, n = su.sorted_union(
            tuple(k[:, j] for k in ka),
            {i: v[:, j] for i, v in enumerate(va)},
            tuple(k[:, j] for k in kb),
            {i: v[:, j] for i, v in enumerate(vb)},
            # plane 0 is key-determined (keep-first == OR); plane 1 is the
            # monotone flag, where the kernel's OR-on-punch applies
            combine=lambda x, y: {0: x[0], 1: x[1] | y[1]},
            out_size=c,
        )
        for k in range(n_keys):
            np.testing.assert_array_equal(
                np.asarray(keys[k]), np.asarray(ko[k][:, j]),
                err_msg=f"key {k}",
            )
        for i in range(n_vals):
            np.testing.assert_array_equal(
                np.asarray(vals[i]), np.asarray(vo[i][:, j]),
                err_msg=f"val {i}",
            )
        assert int(n) == int(nu[j])


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
@pytest.mark.parametrize("stripe", [8, 16, 32, 64])
def test_striped_lexn_matches_fused(stripe):
    """Round-5: the capacity-striped union (block-bitonic merge of sorted
    stripes via the merge-only kernel + XLA dedup/compaction epilogue)
    must be bit-identical to the monolithic fused kernel — including at
    stripe == C (degenerate 2-block network) and with heavy cross-operand
    duplication, at both lossless (2C) and capacity-truncated out sizes."""
    rng = np.random.default_rng(60 + stripe)
    c, lanes, n_keys, n_vals = 64, 128, 3, 2
    ka, va = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    kb, vb = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    for out_size in (None, c):
        want = pallas_union.sorted_union_columnar_fused_lexn(
            tuple(ka), tuple(va), tuple(kb), tuple(vb),
            out_size=out_size, interpret=True,
        )
        got = pallas_union.sorted_union_columnar_striped_lexn(
            tuple(ka), tuple(va), tuple(kb), tuple(vb),
            out_size=out_size, stripe=stripe, interpret=True,
        )
        for w, g in zip(want[0] + want[1] + (want[2],),
                        got[0] + got[1] + (got[2],)):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
@pytest.mark.parametrize("stripe", [8, 32, 64])
def test_striped_kernel_epilogue_matches_sort(stripe):
    """Round-5: the compaction-only Pallas kernel epilogue
    (lexn_compact_columnar — the compiled default on TPU) must be
    bit-identical to the XLA sort epilogue AND to the fused monolith,
    including at truncating out sizes and with heavy duplication."""
    rng = np.random.default_rng(80 + stripe)
    c, lanes, n_keys, n_vals = 64, 128, 3, 2
    ka, va = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    kb, vb = _lexn_cols(rng, c, lanes, n_keys, n_vals, or_plane=1)
    for out_size in (None, c):
        want = pallas_union.sorted_union_columnar_striped_lexn(
            tuple(ka), tuple(va), tuple(kb), tuple(vb),
            out_size=out_size, stripe=stripe, interpret=True,
            epilogue="sort",
        )
        got = pallas_union.sorted_union_columnar_striped_lexn(
            tuple(ka), tuple(va), tuple(kb), tuple(vb),
            out_size=out_size, stripe=stripe, interpret=True,
            epilogue="kernel",
        )
        oracle = pallas_union.sorted_union_columnar_fused_lexn(
            tuple(ka), tuple(va), tuple(kb), tuple(vb),
            out_size=out_size, interpret=True,
        )
        for w, g, o in zip(want[0] + want[1] + (want[2],),
                           got[0] + got[1] + (got[2],),
                           oracle[0] + oracle[1] + (oracle[2],)):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
            np.testing.assert_array_equal(np.asarray(o), np.asarray(g))


def test_lexn_compact_fits_envelope():
    """The compact kernel's envelope admits the production full-depth
    shapes (2C=2048 x 22 planes at C=1024 x D=6) and excludes the next
    doubling; auto epilogue dispatch keys off it."""
    assert pallas_union.lexn_compact_fits(2048, 21)   # C=1024, D=6
    assert pallas_union.lexn_compact_fits(1024, 21)   # C=512, D=6
    assert not pallas_union.lexn_compact_fits(4096, 21)  # C=2048: sort path


def test_lexn_auto_dispatch():
    """The auto entry point picks the monolith inside the VMEM envelope
    and the striped path beyond it, transparently to callers."""
    assert pallas_union.lexn_fits(256, 21)
    assert not pallas_union.lexn_fits(512, 21)
    # stripe selection walks down to a fitting power of two
    assert pallas_union._lexn_stripe_for(1024, 22) == 256
    rng = np.random.default_rng(99)
    c, lanes = 32, 128
    ka, va = _lexn_cols(rng, c, lanes, 3, 2, or_plane=1)
    kb, vb = _lexn_cols(rng, c, lanes, 3, 2, or_plane=1)
    want = pallas_union.sorted_union_columnar_fused_lexn(
        tuple(ka), tuple(va), tuple(kb), tuple(vb),
        out_size=c, interpret=True,
    )
    got = pallas_union.sorted_union_columnar_lexn_auto(
        tuple(ka), tuple(va), tuple(kb), tuple(vb),
        out_size=c, interpret=True,
    )
    for w, g in zip(want[0] + want[1] + (want[2],),
                    got[0] + got[1] + (got[2],)):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
