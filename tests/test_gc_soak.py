"""CI sweep of the set-workload tombstone-GC soak (short schedules; the
long mode mirrors the other fuzz suites' --long / CRDT_LONG knob)."""
import pytest

from crdt_tpu.harness.gc_soak import SetSoakRunner


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_gc_soak_short(seed):
    report = SetSoakRunner(n=4, seed=seed, capacity=256).run(150)
    assert report.steps == 150
    # transparency/safety are asserted inside every step; these pinned
    # seeds all run barriers against workloads with removes, so
    # reclamation must actually fire (checked empirically: 18-26 rows)
    assert report.barriers > report.barriers_noop
    assert report.rows_reclaimed > 0


def test_gc_soak_reclaims_under_pressure():
    """A remove-heavy schedule with frequent barriers must keep the table
    bounded well below the total add count."""
    r = SetSoakRunner(
        n=3, seed=7, capacity=128, p_add=0.35, p_remove=0.25,
        p_join=0.2, p_kill=0.0, p_revive=0.0, p_barrier=0.2,
    ).run(400)
    assert r.barriers - r.barriers_noop >= 3, "need >=3 RECLAIMING barriers"
    assert r.rows_reclaimed > 0
    assert r.final_rows < r.adds, "GC failed to bound tombstone growth"


def test_gc_soak_long(request):
    import os

    # --long (conftest) or CRDT_LONG both enable it, like the other
    # long-mode suites (tests/test_parity_fuzz.py)
    if not (request.config.getoption("--long") or os.environ.get("CRDT_LONG")):
        pytest.skip("long soak: pytest --long (or CRDT_LONG=1)")
    for seed in range(10):
        SetSoakRunner(n=5, seed=seed, capacity=1024).run(1500)


# ---- OR-Map epoch-reset GC (crdt_tpu.models.ormap_gc, round 4) --------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_map_soak_short(seed):
    from crdt_tpu.harness.gc_soak import MapSoakRunner

    report = MapSoakRunner(n=3, seed=seed).run(200)
    assert report.steps == 200
    # M1/M4 are asserted inside every step; every pinned seed runs
    # barriers against a workload with removes, so resets must fire
    assert report.barriers > 0
    assert report.keys_reset > 0


def test_map_soak_reset_under_pressure():
    """Remove-heavy + frequent barriers + stale restores: resets must
    fire repeatedly and stale pre-barrier states must be absorbed by the
    per-key epochs (M2 — implied by M1 across the restore schedule)."""
    from crdt_tpu.harness.gc_soak import MapSoakRunner

    r = MapSoakRunner(
        n=3, seed=5, p_update=0.3, p_remove=0.22, p_join=0.2,
        p_kill=0.0, p_revive=0.0, p_snapshot=0.05, p_restore=0.05,
        p_barrier=0.18,
    ).run(400)
    assert r.keys_reset >= 3
    assert r.restores >= 1


def test_map_gc_join_laws():
    """The epoch-guarded join stays ACI on states with mixed epochs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crdt_tpu.models import ormap, ormap_gc, pncounter

    vjoin = jax.vmap(pncounter.join)
    zero = pncounter.zero(3)

    def mk(seed):
        import random

        rng = random.Random(seed)
        g = ormap_gc.wrap(ormap.empty(6, 3, zero))
        for _ in range(12):
            k, w = rng.randrange(6), rng.randrange(3)
            if rng.random() < 0.7:
                d = rng.randint(-4, 4)
                g = ormap_gc.update(
                    g, k, w, lambda v: pncounter.add(v, w, d)
                )
            else:
                g = ormap_gc.remove(g, k, w)
        # give some keys a nonzero epoch (as a barrier would)
        mask = jnp.asarray([rng.random() < 0.3 for _ in range(6)])
        return ormap_gc.reset_keys(g, mask, zero)

    a, b, c = mk(1), mk(2), mk(3)
    j = lambda x, y: ormap_gc.join(x, y, vjoin)

    def eq(x, y):
        for lx, ly in zip(jax.tree.leaves(x), jax.tree.leaves(y)):
            np.testing.assert_array_equal(np.asarray(lx), np.asarray(ly))

    eq(j(a, b), j(b, a))                    # commutative
    eq(j(j(a, b), c), j(a, j(b, c)))        # associative
    eq(j(a, a), a)                          # idempotent
    eq(j(j(a, b), b), j(a, b))              # absorption
