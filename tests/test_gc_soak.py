"""CI sweep of the set-workload tombstone-GC soak (short schedules; the
long mode mirrors the other fuzz suites' --long / CRDT_LONG knob)."""
import pytest

from crdt_tpu.harness.gc_soak import SetSoakRunner


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_gc_soak_short(seed):
    report = SetSoakRunner(n=4, seed=seed, capacity=256).run(150)
    assert report.steps == 150
    # transparency/safety are asserted inside every step; these pinned
    # seeds all run barriers against workloads with removes, so
    # reclamation must actually fire (checked empirically: 18-26 rows)
    assert report.barriers > report.barriers_noop
    assert report.rows_reclaimed > 0


def test_gc_soak_reclaims_under_pressure():
    """A remove-heavy schedule with frequent barriers must keep the table
    bounded well below the total add count."""
    r = SetSoakRunner(
        n=3, seed=7, capacity=128, p_add=0.35, p_remove=0.25,
        p_join=0.2, p_kill=0.0, p_revive=0.0, p_barrier=0.2,
    ).run(400)
    assert r.barriers - r.barriers_noop >= 3, "need >=3 RECLAIMING barriers"
    assert r.rows_reclaimed > 0
    assert r.final_rows < r.adds, "GC failed to bound tombstone growth"


def test_gc_soak_long(request):
    import os

    # --long (conftest) or CRDT_LONG both enable it, like the other
    # long-mode suites (tests/test_parity_fuzz.py)
    if not (request.config.getoption("--long") or os.environ.get("CRDT_LONG")):
        pytest.skip("long soak: pytest --long (or CRDT_LONG=1)")
    for seed in range(10):
        SetSoakRunner(n=5, seed=seed, capacity=1024).run(1500)


# ---- OR-Map epoch-reset GC (crdt_tpu.models.ormap_gc, round 4) --------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_map_soak_short(seed):
    from crdt_tpu.harness.gc_soak import MapSoakRunner

    report = MapSoakRunner(n=3, seed=seed).run(200)
    assert report.steps == 200
    # M1/M4 are asserted inside every step; every pinned seed runs
    # barriers against a workload with removes, so resets must fire
    assert report.barriers > 0
    assert report.keys_reset > 0


def test_map_soak_reset_under_pressure():
    """Remove-heavy + frequent barriers + stale restores: resets must
    fire repeatedly and stale pre-barrier states must be absorbed by the
    per-key epochs (M2 — implied by M1 across the restore schedule)."""
    from crdt_tpu.harness.gc_soak import MapSoakRunner

    r = MapSoakRunner(
        n=3, seed=5, p_update=0.3, p_remove=0.22, p_join=0.2,
        p_kill=0.0, p_revive=0.0, p_snapshot=0.05, p_restore=0.05,
        p_barrier=0.18,
    ).run(400)
    assert r.keys_reset >= 3
    assert r.restores >= 1


def test_map_gc_join_laws():
    """The epoch-guarded join stays ACI on states with mixed epochs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crdt_tpu.models import ormap, ormap_gc, pncounter

    vjoin = jax.vmap(pncounter.join)
    zero = pncounter.zero(3)

    def mk(seed):
        import random

        rng = random.Random(seed)
        g = ormap_gc.wrap(ormap.empty(6, 3, zero))
        for _ in range(12):
            k, w = rng.randrange(6), rng.randrange(3)
            if rng.random() < 0.7:
                d = rng.randint(-4, 4)
                g = ormap_gc.update(
                    g, k, w, lambda v: pncounter.add(v, w, d)
                )
            else:
                g = ormap_gc.remove(g, k, w)
        # give some keys a nonzero epoch (as a barrier would)
        mask = jnp.asarray([rng.random() < 0.3 for _ in range(6)])
        return ormap_gc.reset_keys(g, mask, zero)

    a, b, c = mk(1), mk(2), mk(3)
    j = lambda x, y: ormap_gc.join(x, y, vjoin)

    def eq(x, y):
        for lx, ly in zip(jax.tree.leaves(x), jax.tree.leaves(y)):
            np.testing.assert_array_equal(np.asarray(lx), np.asarray(ly))

    eq(j(a, b), j(b, a))                    # commutative
    eq(j(j(a, b), c), j(a, j(b, c)))        # associative
    eq(j(a, a), a)                          # idempotent
    eq(j(j(a, b), b), j(a, b))              # absorption


# ---- fleet-coordinated GC: StabilityTracker-driven op-log compaction ----
# (the nemesis --gc soak audits this same path under partitions/crashes;
# here the coordination protocol itself is pinned deterministically)


def _full_exchange(nodes):
    for dst in nodes:
        for src in nodes:
            if src is not dst:
                dst.receive(src.gossip_payload(since=dst.version_vector()))


def _fleet_with_trackers(clock):
    from crdt_tpu.api.node import ReplicaNode
    from crdt_tpu.consistency import StabilityTracker

    nodes = [ReplicaNode(rid=i, capacity=64) for i in range(3)]
    labels = [f"n{i}" for i in range(3)]
    trackers = [
        StabilityTracker(n, [m for j, m in enumerate(labels) if j != i],
                         clock=clock, events=n.events)
        for i, n in enumerate(nodes)
    ]
    return nodes, labels, trackers


def test_fleet_coordinated_gc_compacts_stable_prefix():
    from crdt_tpu.api.node import ReplicaNode
    from crdt_tpu.consistency import decode_summary, encode_summary

    nodes, labels, trackers = _fleet_with_trackers(lambda: 0.0)
    for i, n in enumerate(nodes):
        n.add_commands([{f"k{i}-{j}": f"v{j}"} for j in range(5)])
    _full_exchange(nodes)
    before = [n.get_state() for n in nodes]
    assert before[0] == before[1] == before[2]

    # feed every tracker through the real header encoding (what the
    # transport captures off GET /gossip responses)
    for i, tr in enumerate(trackers):
        for j, src in enumerate(nodes):
            if j == i:
                continue
            vv, frontier = src.vv_snapshot()
            s = decode_summary(encode_summary(src.rid, vv, frontier))
            tr.note(labels[j], s["vv"], s["frontier"])

    fronts = [tr.mint(step=1) for tr in trackers]
    # fully exchanged fleet: every tracker proves the same full frontier
    assert fronts[0] == fronts[1] == fronts[2]
    assert fronts[0] == nodes[0].version_vector()

    for n, f in zip(nodes, fronts):
        n.compact(f)
    for n, s in zip(nodes, before):
        assert n.get_state() == s                 # fold is transparent
        assert n.version_vector() == fronts[0]    # watermark preserved
        assert len(n._commands) == 0              # raw rows reclaimed
        assert n.metrics._counts.get("gc_reclaimed_ops", 0) == 15
    assert all(tr.ledger[-1]["frontier"] == fronts[0] for tr in trackers)

    # post-GC nodes still serve joinable payloads (summary sections)
    late = ReplicaNode(rid=9, capacity=64)
    late.receive(nodes[0].gossip_payload(since=late.version_vector()))
    assert late.get_state() == before[0]


def test_fleet_gc_stalls_on_silent_member():
    nodes, labels, trackers = _fleet_with_trackers(lambda: 0.0)
    for i, n in enumerate(nodes):
        n.add_commands([{f"k{i}": "v"}])
    _full_exchange(nodes)

    # tracker 0 hears from n1 but NEVER from n2 (partitioned member)
    vv, frontier = nodes[1].vv_snapshot()
    trackers[0].note(labels[1], vv, frontier)
    assert trackers[0].stale_members() == [labels[2]]
    assert trackers[0].mint(step=1) == {}
    assert trackers[0].ledger == []
    assert nodes[0].events.find(event="stability_stalled")

    # nothing was collected: the full raw history is still servable
    assert len(nodes[0]._commands) == 3
    assert nodes[0].metrics._counts.get("gc_reclaimed_ops", 0) == 0
