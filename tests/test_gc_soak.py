"""CI sweep of the set-workload tombstone-GC soak (short schedules; the
long mode mirrors the other fuzz suites' --long / CRDT_LONG knob)."""
import pytest

from crdt_tpu.harness.gc_soak import SetSoakRunner


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_gc_soak_short(seed):
    report = SetSoakRunner(n=4, seed=seed, capacity=256).run(150)
    assert report.steps == 150
    # transparency/safety are asserted inside every step; these pinned
    # seeds all run barriers against workloads with removes, so
    # reclamation must actually fire (checked empirically: 18-26 rows)
    assert report.barriers > report.barriers_noop
    assert report.rows_reclaimed > 0


def test_gc_soak_reclaims_under_pressure():
    """A remove-heavy schedule with frequent barriers must keep the table
    bounded well below the total add count."""
    r = SetSoakRunner(
        n=3, seed=7, capacity=128, p_add=0.35, p_remove=0.25,
        p_join=0.2, p_kill=0.0, p_revive=0.0, p_barrier=0.2,
    ).run(400)
    assert r.barriers - r.barriers_noop >= 3, "need >=3 RECLAIMING barriers"
    assert r.rows_reclaimed > 0
    assert r.final_rows < r.adds, "GC failed to bound tombstone growth"


def test_gc_soak_long(request):
    import os

    # --long (conftest) or CRDT_LONG both enable it, like the other
    # long-mode suites (tests/test_parity_fuzz.py)
    if not (request.config.getoption("--long") or os.environ.get("CRDT_LONG")):
        pytest.skip("long soak: pytest --long (or CRDT_LONG=1)")
    for seed in range(10):
        SetSoakRunner(n=5, seed=seed, capacity=1024).run(1500)
