"""CompositeNode: the served ``mapof(pncounter)`` composite.

What's pinned here: local op semantics (upd/rem/observed-remove), the
state-based wire (decode validation against nemesis corruption, foreign
coordinate-space alignment), the one-dispatch-per-round fused fold
(``merge_dispatches``), convergence via fingerprints, and the
snapshot-as-wire-payload restore path — plus the NodeHost serving stack
(HTTP routes, agent pulls, fused rounds, checkpoint restore)."""
import threading

import pytest

from crdt_tpu.api.compositenode import CompositeNode


def _pull(dst, src):
    """One state-based pull: dst absorbs src's full dump."""
    return dst.receive(src.gossip_payload())


# ------------------------------------------------------------- local ops


def test_upd_rem_readd_semantics():
    n = CompositeNode(rid=0)
    assert n.upd("x", 5) == 5
    assert n.upd("x", -2) == 3
    assert n.upd("y", 7) == 7
    assert n.items() == {"x": 3, "y": 7}
    assert n.value("x") == 3
    assert n.rem("x") is True
    assert n.items() == {"y": 7}
    assert n.value("x") is None
    # removing an absent / already-removed key mints nothing
    assert n.rem("x") is False
    assert n.rem("never-seen") is False
    # a re-add drops a fresh token that the old observation doesn't cover;
    # the PN planes survive removal (counter semantics: remove hides the
    # key, it doesn't zero history)
    assert n.upd("x", 1) == 4
    assert n.items() == {"x": 4, "y": 7}


def test_down_node_refuses_ops():
    n = CompositeNode(rid=0)
    n.upd("x", 1)
    n.set_alive(False)
    assert not n.ping()
    assert n.upd("x", 1) is None
    assert n.rem("x") is None
    assert n.items() is None
    assert n.gossip_payload() is None
    n.set_alive(True)
    assert n.items() == {"x": 1}


def test_capacity_growth_past_initial():
    n = CompositeNode(rid=0, n_keys=2, n_writers=2)
    for i in range(9):
        n.upd(f"k{i}", i)
    assert n.items() == {f"k{i}": i for i in range(9)}
    # writer growth comes from foreign rids arriving on the wire
    peers = [CompositeNode(rid=r) for r in range(3, 8)]
    for p in peers:
        p.upd("shared", 1)
        _pull(n, p)
    assert n.items()["shared"] == 5


# ------------------------------------------------------ wire validation


def test_decode_rejects_nemesis_corruption():
    n = CompositeNode(rid=0)
    n.upd("x", 1)
    good = n.gossip_payload()

    # the FaultyTransport corrupt fault: first non-dunder section poisoned
    # + marker added (faults/transport.py) — both independently fatal
    poisoned = dict(good)
    poisoned["keys"] = "corrupted-by-nemesis"
    poisoned["__nemesis_corrupt__"] = 1
    with pytest.raises(ValueError):
        CompositeNode.decode(poisoned)
    marker_only = dict(good)
    marker_only["__nemesis_corrupt__"] = 1
    with pytest.raises(ValueError):
        CompositeNode.decode(marker_only)
    keys_only = dict(good)
    keys_only["keys"] = "corrupted-by-nemesis"
    with pytest.raises(ValueError):
        CompositeNode.decode(keys_only)


@pytest.mark.parametrize("mutate", [
    lambda p: 42,                                     # not an object
    lambda p: {**p, "writers": ["zero"]},             # non-int rids
    lambda p: {**p, "keys": p["keys"] * 2},           # duplicate keys
    lambda p: {**p, "tok": [[1, 2, 3]]},              # plane shape mismatch
    lambda p: {**p, "obs": p["tok"]},                 # missing writer axis
    lambda p: {**p, "pos": "corrupted-by-nemesis"},   # poisoned plane
    lambda p: {k: v for k, v in p.items() if k != "neg"},  # plane dropped
])
def test_decode_rejects_malformed_payloads(mutate):
    n = CompositeNode(rid=0)
    n.upd("x", 1)
    with pytest.raises(ValueError):
        CompositeNode.decode(mutate(n.gossip_payload()))


def test_empty_payload_roundtrips():
    a, b = CompositeNode(rid=0), CompositeNode(rid=1)
    assert _pull(a, b) == 0  # nothing to learn, and nothing blows up
    assert a.items() == {}


# -------------------------------------------------- merge + convergence


def test_merge_decoded_is_one_dispatch_for_k_payloads():
    """The PR-2 fused-ingest discipline: folding k peer payloads costs the
    same single jitted dispatch as folding one."""
    n = CompositeNode(rid=0)
    n.upd("x", 1)
    payloads = []
    for r in range(1, 6):
        p = CompositeNode(rid=r)
        p.upd("x", 1)
        p.upd(f"only-{r}", r)
        payloads.append(CompositeNode.decode(p.gossip_payload()))
    before = n.merge_dispatches
    assert n.merge_decoded(payloads) == 1
    assert n.merge_dispatches == before + 1
    assert int(n.metrics.registry.counter_value(
        "composite_merge_dispatches")) == 1
    assert n.items()["x"] == 6
    assert n.items()["only-3"] == 3


def test_two_node_convergence_and_idempotence():
    a, b = CompositeNode(rid=0), CompositeNode(rid=9)
    a.upd("x", 5)
    a.upd("z", 1)
    b.upd("x", -2)
    b.upd("y", 7)
    # intern orders differ (a: x,z then y; b: x,y then z) — alignment by
    # key string / writer rid, not by slot index
    assert _pull(a, b) == 1
    assert _pull(b, a) == 1
    assert a.items() == b.items() == {"x": 3, "y": 7, "z": 1}
    assert a.fingerprint() == b.fingerprint()
    # idempotence on the wire: replaying the same payload is a no-op
    assert _pull(a, b) == 0
    assert a.fingerprint() == b.fingerprint()


def test_observed_remove_across_the_wire():
    a, b = CompositeNode(rid=0), CompositeNode(rid=1)
    a.upd("x", 4)
    _pull(b, a)                      # b observes a's token
    assert b.rem("x") is True
    a.upd("x", 2)                    # concurrent re-add: fresh token
    _pull(a, b)
    _pull(b, a)
    # the remove killed the observed token; the concurrent add survives
    assert a.items() == b.items() == {"x": 6}
    # a remove that HAS observed everything hides the key on both sides
    assert a.rem("x") is True
    _pull(b, a)
    assert a.items() == b.items() == {}
    assert a.fingerprint() == b.fingerprint()


def test_three_node_gossip_converges():
    nodes = [CompositeNode(rid=r) for r in (2, 5, 11)]
    nodes[0].upd("a", 1)
    nodes[1].upd("a", 10)
    nodes[1].rem("a")
    nodes[2].upd("b", -3)
    for _ in range(2):               # two full rings reach everyone
        for i, src in enumerate(nodes):
            _pull(nodes[(i + 1) % 3], src)
    fps = [n.fingerprint() for n in nodes]
    assert fps[0] == fps[1] == fps[2]
    # node 1's remove only observed its own local state at remove time;
    # node 0's token was not yet seen there, so "a" survives
    assert nodes[0].items() == {"a": 11, "b": -3}


# -------------------------------------------------------------- snapshot


def test_snapshot_roundtrip():
    n = CompositeNode(rid=3)
    n.upd("x", 5)
    n.upd("y", -1)
    n.rem("y")
    snap = n.to_snapshot()
    fresh = CompositeNode(rid=3)
    fresh.from_snapshot(snap)
    assert fresh.fingerprint() == n.fingerprint()
    assert fresh.items() == {"x": 5}
    # restored node keeps converging normally
    peer = CompositeNode(rid=4)
    peer.upd("x", 1)
    _pull(fresh, peer)
    assert fresh.items() == {"x": 6}


def test_corrupt_snapshot_fails_restore():
    """from_snapshot validates like a wire payload — a flipped-bit
    composite.json raises instead of resurrecting garbage (checkpoint
    loader then quarantines the snapshot generation)."""
    n = CompositeNode(rid=0)
    n.upd("x", 1)
    snap = n.to_snapshot()
    snap["tok"] = "corrupted"
    with pytest.raises(ValueError):
        CompositeNode(rid=0).from_snapshot(snap)


# ------------------------------------------------- NodeHost serving stack


def _serve(*hosts):
    from crdt_tpu.api.net import RemotePeer

    for h in hosts:
        h.agent.peers = [RemotePeer(o.url) for o in hosts if o is not h]
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()


def _shutdown(*hosts):
    for h in hosts:
        h._server.shutdown()
        h._server.server_close()


def test_nodehost_http_surface_and_pull():
    import json
    import urllib.request

    from crdt_tpu.api.net import NodeHost

    a, b = NodeHost(rid=0, peers=[]), NodeHost(rid=1, peers=[])
    _serve(a, b)
    try:
        def post(url, path, body):
            req = urllib.request.Request(
                url + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=5) as res:
                return json.loads(res.read())

        assert post(a.url, "/composite/upd",
                    {"key": "x", "delta": 5}) == {"value": 5}
        assert post(b.url, "/composite/upd",
                    {"key": "x", "delta": -2}) == {"value": -2}
        assert post(b.url, "/composite/upd",
                    {"key": "y", "delta": 7}) == {"value": 7}
        # gossip_once carries the composite alongside KV/set/seq/map
        a.agent.gossip_once()
        b.agent.gossip_once()
        want = {"x": 3, "y": 7}
        assert a.composite_node.items() == b.composite_node.items() == want
        with urllib.request.urlopen(a.url + "/composite", timeout=5) as res:
            assert json.loads(res.read()) == {"items": want}
        # observed-remove over HTTP, then the admin drive surface
        assert post(a.url, "/composite/rem", {"key": "y"}) == {
            "removed": True}
        assert post(b.url, "/admin/composite_pull", {}) == {"pulled": True}
        assert b.composite_node.items() == {"x": 3}
        # /metrics exposes the composite health gauges
        with urllib.request.urlopen(a.url + "/metrics", timeout=5) as res:
            body = res.read().decode()
        assert "composite_keys" in body
        assert "composite_merge_dispatches" in body
    finally:
        _shutdown(a, b)


def test_fused_round_folds_composite_in_one_dispatch():
    """config.fuse_pull_k > 1: the composite leg of a fused round fetches
    every responding peer's state and folds ALL of them in one dispatch."""
    from crdt_tpu.api.net import NodeHost
    from crdt_tpu.utils.config import ClusterConfig

    cfg = ClusterConfig(fuse_pull_k=2)
    hosts = [NodeHost(rid=r, peers=[], config=cfg) for r in range(3)]
    _serve(*hosts)
    try:
        for i, h in enumerate(hosts):
            h.composite_node.upd("x", i + 1)
        before = hosts[0].composite_node.merge_dispatches
        hosts[0].agent.gossip_once()
        assert hosts[0].composite_node.merge_dispatches == before + 1
        assert hosts[0].composite_node.items() == {"x": 6}
    finally:
        _shutdown(*hosts)


def test_nodehost_checkpoint_roundtrips_composite(tmp_path):
    from crdt_tpu.api.net import NodeHost

    d = str(tmp_path / "ckpt")
    a = NodeHost(rid=0, peers=[], checkpoint_dir=d)
    a.composite_node.upd("x", 5)
    a.composite_node.upd("y", 1)
    a.composite_node.rem("y")
    assert a.checkpoint_now() is not None
    fp = a.composite_node.fingerprint()
    a._server.server_close()

    b = NodeHost(rid=0, peers=[], checkpoint_dir=d)
    try:
        assert b.restored
        assert b.composite_node.fingerprint() == fp
        assert b.composite_node.items() == {"x": 5}
    finally:
        b._server.server_close()
