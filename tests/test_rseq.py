"""Sequence CRDT tests (crdt_tpu.models.rseq): join laws, RGA-style
concurrent-edit semantics, and the host editing cursor."""
import zlib

import numpy as np
import pytest

from crdt_tpu.models import rseq
from tests.helpers import tree_equal

N_TRIALS = 20
CAP = 64


_next_rid = iter(range(10_000))


def _rand_seq(rng: np.random.Generator) -> rseq.RSeq:
    # each generated state gets a FRESH writer id: (rid, seq) identities
    # must be globally writer-unique — the precondition every real
    # deployment upholds (ClusterConfig.rid_base) — else two states could
    # carry the same identity with different payloads
    w = rseq.SeqWriter(rseq.empty(CAP), rid=next(_next_rid))
    for _ in range(rng.integers(0, 10)):
        n = len(w.to_list())
        if n and rng.random() < 0.25:
            w.delete_at(int(rng.integers(0, n)))
        else:
            w.insert_at(int(rng.integers(0, n + 1)), int(rng.integers(0, 100)))
    return w.state


def test_join_laws():
    rng = np.random.default_rng(zlib.crc32(b"rseq"))
    for _ in range(N_TRIALS):
        a, b, c = _rand_seq(rng), _rand_seq(rng), _rand_seq(rng)
        assert tree_equal(rseq.join(a, b), rseq.join(b, a)), "commutativity"
        assert tree_equal(
            rseq.join(rseq.join(a, b), c), rseq.join(a, rseq.join(b, c))
        ), "associativity"
        assert tree_equal(rseq.join(a, a), a), "idempotence"
        assert tree_equal(rseq.join(a, rseq.empty(CAP)), a), "identity"


def test_sequential_editing():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for ch in [10, 20, 30]:
        w.append(ch)
    assert w.to_list() == [10, 20, 30]
    w.insert_at(1, 15)
    assert w.to_list() == [10, 15, 20, 30]
    w.delete_at(2)
    assert w.to_list() == [10, 15, 30]
    w.insert_at(0, 5)
    assert w.to_list() == [5, 10, 15, 30]


def test_concurrent_inserts_converge_deterministically():
    """Two writers insert into the SAME gap concurrently: after exchanging
    states both read the same list, ordered by writer id at the collision
    point (the RGA interleaving rule)."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)   # both target the gap between 1 and 4
    b.insert_at(1, 3)
    merged_ab = rseq.join(a.state, b.state)
    merged_ba = rseq.join(b.state, a.state)
    assert rseq.to_list(merged_ab) == rseq.to_list(merged_ba)
    assert rseq.to_list(merged_ab) == [1, 2, 3, 4]  # rid 1 before rid 2


def test_concurrent_insert_and_delete():
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for ch in [1, 2, 3]:
        base.append(ch)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.delete_at(1)      # remove 2
    b.insert_at(2, 9)   # insert 9 between 2 and 3 (concurrent)
    m = rseq.join(a.state, b.state)
    assert rseq.to_list(m) == [1, 9, 3]  # delete won; insert survives
    assert int(rseq.size(m)) == 3


def test_delete_is_permanent_tombstone():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    w.append(7)
    before = w.state
    w.delete_at(0)
    # re-merging the pre-delete state cannot resurrect the element
    m = rseq.join(w.state, before)
    assert rseq.to_list(m) == []


def test_interleaved_convergence_three_writers():
    rng = np.random.default_rng(5)
    base = rseq.empty(128)
    writers = [rseq.SeqWriter(base, rid=r) for r in range(3)]
    for step in range(30):
        w = writers[rng.integers(0, 3)]
        n = len(w.to_list())
        if n and rng.random() < 0.3:
            w.delete_at(int(rng.integers(0, n)))
        else:
            w.insert_at(int(rng.integers(0, n + 1)), int(rng.integers(0, 100)))
        if step % 7 == 6:  # periodic pairwise gossip
            i, j = rng.choice(3, size=2, replace=False)
            m = rseq.join(writers[i].state, writers[j].state)
            writers[i].state = m
            writers[j].state = m
    top = writers[0].state
    for w in writers[1:]:
        top = rseq.join(top, w.state)
    for w in writers:
        assert rseq.to_list(rseq.join(w.state, top)) == rseq.to_list(top)


def test_insert_between_collided_pair():
    """Regression: two writers concurrently insert into the same gap, get
    the same level-1 midpoint (tie-broken by rid), and a third writer then
    inserts BETWEEN the collided pair — this must go deep (anchor on the
    left neighbour), not crash."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)
    b.insert_at(1, 3)
    m = rseq.SeqWriter(rseq.join(a.state, b.state), rid=3)
    assert m.to_list() == [1, 2, 3, 4]
    m.insert_at(2, 99)  # between the tie-broken twins: deep insert
    assert m.to_list() == [1, 2, 99, 3, 4]
    # and editing around the deep element keeps working
    m.insert_at(3, 98)
    assert m.to_list() == [1, 2, 99, 98, 3, 4]
    m.insert_at(2, 97)
    assert m.to_list() == [1, 2, 97, 99, 98, 3, 4]
    m.delete_at(3)
    assert m.to_list() == [1, 2, 97, 98, 3, 4]


def test_deep_inserts_converge_across_writers():
    """Deep (level-2) elements travel through joins like any other row."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)
    b.insert_at(1, 3)
    merged = rseq.join(a.state, b.state)
    x = rseq.SeqWriter(merged, rid=3)
    y = rseq.SeqWriter(merged, rid=4)
    x.insert_at(2, 50)  # both go deep between the collided pair
    y.insert_at(2, 60)
    m1 = rseq.to_list(rseq.join(x.state, y.state))
    m2 = rseq.to_list(rseq.join(y.state, x.state))
    assert m1 == m2
    assert m1 == [1, 2, 50, 60, 4] or m1 == [1, 2, 50, 60, 3, 4]
    assert set(m1) == {1, 2, 3, 4, 50, 60}


def test_gap_exhaustion_raises():
    with pytest.raises(rseq.GapExhausted):
        rseq._alloc(100, 101, stride_edges=False)
    assert 100 < rseq._alloc(100, 103, stride_edges=False) < 103


def test_append_and_prepend_use_stride_not_bisection():
    w = rseq.SeqWriter(rseq.empty(256), rid=0)
    for i in range(80):  # far more than 60-bit bisection could survive
        w.append(i)
    assert w.to_list() == list(range(80))
    w2 = rseq.SeqWriter(rseq.empty(256), rid=1)
    for i in range(80):
        w2.insert_at(0, i)
    assert w2.to_list() == list(range(79, -1, -1))
