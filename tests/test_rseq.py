"""Sequence CRDT tests (crdt_tpu.models.rseq): join laws, RGA-style
concurrent-edit semantics, and the host editing cursor."""
import zlib

import numpy as np
import pytest

from crdt_tpu.models import rseq
from tests.helpers import tree_equal

N_TRIALS = 20
CAP = 64


_next_rid = iter(range(10_000))


def _rand_seq(rng: np.random.Generator) -> rseq.RSeq:
    # each generated state gets a FRESH writer id: (rid, seq) identities
    # must be globally writer-unique — the precondition every real
    # deployment upholds (ClusterConfig.rid_base) — else two states could
    # carry the same identity with different payloads
    w = rseq.SeqWriter(rseq.empty(CAP), rid=next(_next_rid))
    for _ in range(rng.integers(0, 10)):
        n = len(w.to_list())
        if n and rng.random() < 0.25:
            w.delete_at(int(rng.integers(0, n)))
        else:
            w.insert_at(int(rng.integers(0, n + 1)), int(rng.integers(0, 100)))
    return w.state


def test_join_laws():
    rng = np.random.default_rng(zlib.crc32(b"rseq"))
    for _ in range(N_TRIALS):
        a, b, c = _rand_seq(rng), _rand_seq(rng), _rand_seq(rng)
        assert tree_equal(rseq.join(a, b), rseq.join(b, a)), "commutativity"
        assert tree_equal(
            rseq.join(rseq.join(a, b), c), rseq.join(a, rseq.join(b, c))
        ), "associativity"
        assert tree_equal(rseq.join(a, a), a), "idempotence"
        assert tree_equal(rseq.join(a, rseq.empty(CAP)), a), "identity"


def test_sequential_editing():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for ch in [10, 20, 30]:
        w.append(ch)
    assert w.to_list() == [10, 20, 30]
    w.insert_at(1, 15)
    assert w.to_list() == [10, 15, 20, 30]
    w.delete_at(2)
    assert w.to_list() == [10, 15, 30]
    w.insert_at(0, 5)
    assert w.to_list() == [5, 10, 15, 30]


def test_concurrent_inserts_converge_deterministically():
    """Two writers insert into the SAME gap concurrently: after exchanging
    states both read the same list, ordered by writer id at the collision
    point (the RGA interleaving rule)."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)   # both target the gap between 1 and 4
    b.insert_at(1, 3)
    merged_ab = rseq.join(a.state, b.state)
    merged_ba = rseq.join(b.state, a.state)
    assert rseq.to_list(merged_ab) == rseq.to_list(merged_ba)
    assert rseq.to_list(merged_ab) == [1, 2, 3, 4]  # rid 1 before rid 2


def test_concurrent_insert_and_delete():
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for ch in [1, 2, 3]:
        base.append(ch)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.delete_at(1)      # remove 2
    b.insert_at(2, 9)   # insert 9 between 2 and 3 (concurrent)
    m = rseq.join(a.state, b.state)
    assert rseq.to_list(m) == [1, 9, 3]  # delete won; insert survives
    assert int(rseq.size(m)) == 3


def test_delete_is_permanent_tombstone():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    w.append(7)
    before = w.state
    w.delete_at(0)
    # re-merging the pre-delete state cannot resurrect the element
    m = rseq.join(w.state, before)
    assert rseq.to_list(m) == []


def test_interleaved_convergence_three_writers():
    rng = np.random.default_rng(5)
    base = rseq.empty(128)
    writers = [rseq.SeqWriter(base, rid=r) for r in range(3)]
    for step in range(30):
        w = writers[rng.integers(0, 3)]
        n = len(w.to_list())
        if n and rng.random() < 0.3:
            w.delete_at(int(rng.integers(0, n)))
        else:
            w.insert_at(int(rng.integers(0, n + 1)), int(rng.integers(0, 100)))
        if step % 7 == 6:  # periodic pairwise gossip
            i, j = rng.choice(3, size=2, replace=False)
            m = rseq.join(writers[i].state, writers[j].state)
            writers[i].state = m
            writers[j].state = m
    top = writers[0].state
    for w in writers[1:]:
        top = rseq.join(top, w.state)
    for w in writers:
        assert rseq.to_list(rseq.join(w.state, top)) == rseq.to_list(top)


def test_insert_between_collided_pair():
    """Regression: two writers concurrently insert into the same gap, get
    the same level-1 midpoint (tie-broken by rid), and a third writer then
    inserts BETWEEN the collided pair — this must go deep (anchor on the
    left neighbour), not crash."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)
    b.insert_at(1, 3)
    m = rseq.SeqWriter(rseq.join(a.state, b.state), rid=3)
    assert m.to_list() == [1, 2, 3, 4]
    m.insert_at(2, 99)  # between the tie-broken twins: deep insert
    assert m.to_list() == [1, 2, 99, 3, 4]
    # and editing around the deep element keeps working
    m.insert_at(3, 98)
    assert m.to_list() == [1, 2, 99, 98, 3, 4]
    m.insert_at(2, 97)
    assert m.to_list() == [1, 2, 97, 99, 98, 3, 4]
    m.delete_at(3)
    assert m.to_list() == [1, 2, 97, 98, 3, 4]


def test_deep_inserts_converge_across_writers():
    """Deep (level-2) elements travel through joins like any other row."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    a.insert_at(1, 2)
    b.insert_at(1, 3)
    merged = rseq.join(a.state, b.state)
    x = rseq.SeqWriter(merged, rid=3)
    y = rseq.SeqWriter(merged, rid=4)
    x.insert_at(2, 50)  # both go deep between the collided pair
    y.insert_at(2, 60)
    m1 = rseq.to_list(rseq.join(x.state, y.state))
    m2 = rseq.to_list(rseq.join(y.state, x.state))
    assert m1 == m2
    assert m1 == [1, 2, 50, 60, 4] or m1 == [1, 2, 50, 60, 3, 4]
    assert set(m1) == {1, 2, 3, 4, 50, 60}


def test_gap_exhaustion_raises():
    with pytest.raises(rseq.GapExhausted):
        rseq._alloc_between(100, 101, open_lo=False, open_hi=False)
    assert 100 < rseq._alloc_between(
        100, 103, open_lo=False, open_hi=False
    ) < 103
    # MID is reserved for stamp rows and never allocated
    with pytest.raises(rseq.GapExhausted):
        rseq._alloc_between(rseq.MID - 1, rseq.MID + 1,
                            open_lo=False, open_hi=False)
    p = rseq._alloc_between(rseq.MID - 2, rseq.MID + 1,
                            open_lo=False, open_hi=False)
    assert p != rseq.MID


def test_no_character_interleaving_forward_runs():
    """Two writers type runs concurrently into the SAME gap; after the join
    each run must stay contiguous (the RGA/Fugue forward-typing guarantee —
    the round-1 verdict's required property test).  Checked for fresh gaps,
    gaps between existing elements, and at the document end."""
    for prefix, suffix in ([], []), ([1], [9]), ([1, 2], []), ([], [9]):
        base = rseq.SeqWriter(rseq.empty(256), rid=0)
        for i, ch in enumerate(prefix + suffix):
            base.insert_at(i, ch)
        gap = len(prefix)
        x = rseq.SeqWriter(base.state, rid=1)
        y = rseq.SeqWriter(base.state, rid=2)
        run_x = [100 + i for i in range(12)]
        run_y = [200 + i for i in range(9)]
        for i, ch in enumerate(run_x):   # forward typing: each char goes
            x.insert_at(gap + i, ch)     # right after the previous one
        for i, ch in enumerate(run_y):
            y.insert_at(gap + i, ch)
        merged = rseq.to_list(rseq.join(x.state, y.state))
        assert merged == prefix + run_x + run_y + suffix, (prefix, suffix)


def test_no_interleaving_after_collision_point():
    """Same property when the runs start on TOP of an existing tie-broken
    collision pair (regression: the old two-level scheme interleaved here)."""
    base = rseq.SeqWriter(rseq.empty(512), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=2)
    for i in range(20):
        a.insert_at(1 + i, 100 + i)
    for i in range(20):
        b.insert_at(1 + i, 200 + i)
    merged = rseq.to_list(rseq.join(a.state, b.state))
    assert merged == [1] + [100 + i for i in range(20)] + \
        [200 + i for i in range(20)] + [4]


def test_same_gap_storm_10k_alloc_level():
    """10K-op adversarial same-gap insert storm (verdict item 6 'done'
    criterion), allocation-level: three writers with interleaved schedules
    keep inserting at one fixed index; no GapExhausted, final order
    correct, and the keys really sort the way the inserts intended."""
    rng = np.random.default_rng(0)
    l_row = rseq.alloc_key(None, None, 99, 0)
    r_row = rseq.alloc_key(l_row, None, 99, 1)
    # (key_row, label) in intended order, newest-at-gap-front semantics:
    # every insert lands between l_row and the previously inserted element
    rows = []
    seqs = {1: 0, 2: 0, 3: 0}
    for i in range(10_000):
        rid = int(rng.integers(1, 4))
        right = rows[0][0] if rows else r_row
        key = rseq.alloc_key(l_row, right, rid, seqs[rid], rseq.DEPTH)
        seqs[rid] += 1
        rows.insert(0, (key, i))
    ordered = sorted([(l_row, -1)] + rows + [(r_row, 10_000)],
                     key=lambda kv: kv[0])
    labels = [lab for _, lab in ordered]
    assert labels[0] == -1 and labels[-1] == 10_000
    assert labels[1:-1] == list(range(9_999, -1, -1))


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_same_gap_storm_device_table():
    """A 1.5K-op fixed-index storm through the real device table: no
    GapExhausted, no capacity overflow, order preserved end to end."""
    w = rseq.SeqWriter(rseq.empty(2048), rid=0)
    w.append(-1)
    w.append(-2)
    n = 1500
    for i in range(n):
        w.insert_at(1, i)   # always between -1 and the newest element
    assert w.to_list() == [-1] + list(range(n - 1, -1, -1)) + [-2]


def test_forward_typing_run_keeps_depth_flat():
    """A long single-writer typing run must not grow path depth per char
    (sibling continuation): depth stays ≤ anchor depth + 1."""
    w = rseq.SeqWriter(rseq.empty(1024), rid=7)
    for i in range(600):
        w.insert_at(i, i)
    rows = w._rows()
    depths = {rseq.real_depth(rseq._triples(r, rseq.DEPTH)) for r in rows}
    assert max(depths) <= 2, depths


def test_capacity_exceeded_raises_loudly():
    """ADVICE round 1: a full table must refuse inserts, not silently drop
    the largest position key — and tombstones count as occupancy."""
    w = rseq.SeqWriter(rseq.empty(8), rid=0)
    for i in range(8):
        w.append(i)
    with pytest.raises(rseq.CapacityExceeded):
        w.append(99)
    w.delete_at(0)  # tombstone frees nothing until GC
    with pytest.raises(rseq.CapacityExceeded):
        w.append(99)


def test_nested_collisions_survive_beyond_two_levels():
    """Adversarial nested midpoint collisions: pairs of writers repeatedly
    collide inside the same gap, then a third inserts between the collided
    twins — the round-1 design died at two levels; this must keep going."""
    base = rseq.SeqWriter(rseq.empty(512), rid=0)
    base.append(1)
    base.append(2)
    state = base.state
    rid = 10
    for round_ in range(8):
        a = rseq.SeqWriter(state, rid=rid)
        b = rseq.SeqWriter(state, rid=rid + 1)
        a.insert_at(1, 100 + round_)        # same gap, concurrently
        b.insert_at(1, 200 + round_)
        state = rseq.join(a.state, b.state)
        c = rseq.SeqWriter(state, rid=rid + 2)
        c.insert_at(2, 300 + round_)        # between the collided twins
        state = c.state
        rid += 3
    lst = rseq.to_list(state)
    assert len(lst) == 2 + 8 * 3
    assert lst[0] == 1 and lst[-1] == 2


def test_random_fuzz_converges_and_preserves_intent():
    """Randomized concurrent editing: writers branch, edit independently,
    and every pairwise join must agree regardless of order; every insert's
    (left, right) intention is checked by alloc_key's internal guard."""
    rng = np.random.default_rng(1234)
    for trial in range(10):
        base = rseq.SeqWriter(rseq.empty(512), rid=0)
        for i in range(rng.integers(0, 6)):
            base.insert_at(i, i)
        writers = [
            rseq.SeqWriter(base.state, rid=1 + k) for k in range(3)
        ]
        for w in writers:
            for _ in range(rng.integers(5, 25)):
                n = len(w.to_list())
                if n and rng.random() < 0.3:
                    w.delete_at(int(rng.integers(0, n)))
                else:
                    w.insert_at(int(rng.integers(0, n + 1)),
                                int(rng.integers(0, 1000)))
        states = [w.state for w in writers]
        top = states[0]
        for s in states[1:]:
            top = rseq.join(top, s)
        lists = {tuple(rseq.to_list(rseq.join(s, top))) for s in states}
        assert len(lists) == 1, f"trial {trial} diverged"


def test_identity_escape_between_deepest_level_twins():
    """Collision twins identical through a level now admit an insert AT
    the shared coordinate when the writer's identity sorts between them
    (regression for the seq-soak GapExhausted at the depth cap)."""
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    base.append(1)
    base.append(4)
    a = rseq.SeqWriter(base.state, rid=1)
    b = rseq.SeqWriter(base.state, rid=3)
    a.insert_at(1, 2)   # same gap: same midpoint, tie-broken by rid
    b.insert_at(1, 3)
    merged = rseq.join(a.state, b.state)
    # rid 2 sorts between the twins' rids 1 and 3: the escape places it
    # at the SAME coordinate with its own identity, no extra depth
    m = rseq.SeqWriter(merged, rid=2)
    m.insert_at(2, 99)
    assert m.to_list() == [1, 2, 99, 3, 4]
    rows = m._rows()
    depths = [rseq.real_depth(rseq._triples(r, rseq.DEPTH)) for r in rows]
    assert max(depths) == 2  # twins at 2; 99 escaped at their level


def test_widen_preserves_order_and_join_round_trip():
    w = rseq.SeqWriter(rseq.empty(CAP, depth=3), rid=0)
    for i in range(10):
        w.insert_at(i // 2, i)
    w.delete_at(3)
    before = w.to_list()
    wide = rseq.widen(w.state, 6)
    assert wide.depth == 6
    assert rseq.to_list(wide) == before
    # editing and joining continue in the widened world
    w2 = rseq.SeqWriter(wide, rid=1)
    w2.insert_at(2, 99)
    assert w2.to_list()[2] == 99
    m = rseq.join(wide, w2.state)
    assert rseq.to_list(m) == w2.to_list()
    with pytest.raises(ValueError, match="narrow"):
        rseq.widen(wide, 3)
    # mixed depths must refuse to join, not silently truncate
    with pytest.raises(ValueError, match="shapes differ"):
        rseq.join(w.state, wide)


def test_widen_unblocks_depth_cap_exhaustion():
    """The exact deepest-twin scenario the seq soak found: a writer whose
    rid sorts at-or-above BOTH twins' cannot identity-escape at the shared
    coordinate; at the depth cap that insert is unrepresentable until
    widen() adds a level."""
    base = rseq.SeqWriter(rseq.empty(CAP, depth=2), rid=0)
    base.append(1)
    base.append(4)
    state = base.state
    # concurrent same-gap inserts descend under element 1 and collide at
    # level 2 — the cap
    a = rseq.SeqWriter(state, rid=1)
    b = rseq.SeqWriter(state, rid=2)
    a.insert_at(1, 10)
    b.insert_at(1, 11)
    state = rseq.join(a.state, b.state)
    assert rseq.to_list(state) == [1, 10, 11, 4]
    w = rseq.SeqWriter(state, rid=9)  # rid 9 > both twins: no escape fits
    with pytest.raises(rseq.GapExhausted):
        w.insert_at(2, 99)
    wide = rseq.SeqWriter(rseq.widen(state, 4), rid=9)
    wide.insert_at(2, 99)  # descends to level 3 in the widened table
    assert wide.to_list() == [1, 10, 99, 11, 4]
    # a writer whose identity DOES fit needs no widening even at the cap
    w1 = rseq.SeqWriter(state, rid=1)
    w1.insert_at(2, 55)
    assert w1.to_list() == [1, 10, 55, 11, 4]


def test_insert_run_single_union_matches_per_char():
    """insert_run == the same run typed char by char (order, identities
    aside), costs one union, and respects capacity + contiguity."""
    w = rseq.SeqWriter(rseq.empty(64), rid=1)
    w.append(1)
    w.append(9)
    w.insert_run(1, [100, 101, 102, 103])
    assert w.to_list() == [1, 100, 101, 102, 103, 9]
    assert w._seq == 6
    w.insert_run(None, [7, 8])  # append mode
    assert w.to_list() == [1, 100, 101, 102, 103, 9, 7, 8]
    with pytest.raises(rseq.CapacityExceeded):
        w.insert_run(0, list(range(100)))
    w.insert_run(3, [])  # empty run is a no-op
    assert w._seq == 8


def test_concurrent_insert_runs_do_not_interleave():
    """The batched API preserves the forward-typing non-interleaving
    guarantee: two writers insert_run into the same gap concurrently and
    the runs stay contiguous after the join."""
    base = rseq.SeqWriter(rseq.empty(128), rid=0)
    base.append(1)
    base.append(9)
    x = rseq.SeqWriter(base.state, rid=1)
    y = rseq.SeqWriter(base.state, rid=2)
    x.insert_run(1, [100 + i for i in range(10)])
    y.insert_run(1, [200 + i for i in range(7)])
    merged = rseq.to_list(rseq.join(x.state, y.state))
    assert merged == [1] + [100 + i for i in range(10)] + \
        [200 + i for i in range(7)] + [9]


def test_seqwriter_restart_does_not_remint_identities():
    """A restarted writer (default seq_start) must resume ABOVE its own
    largest in-table seq — re-minting a used (rid, seq) would collide two
    distinct elements (and be silently GC-suppressed under tomb_gc)."""
    w = rseq.SeqWriter(rseq.empty(CAP), rid=3)
    for i in range(5):
        w.append(i)
    w2 = rseq.SeqWriter(w.state, rid=3)  # restart, counter not persisted
    assert w2._seq == 5
    w2.append(99)
    assert w2.to_list() == [0, 1, 2, 3, 4, 99]
    # a different writer starts fresh at 0
    assert rseq.SeqWriter(w.state, rid=4)._seq == 0


def test_append_and_prepend_use_stride_not_bisection():
    w = rseq.SeqWriter(rseq.empty(256), rid=0)
    for i in range(80):  # far more than 60-bit bisection could survive
        w.append(i)
    assert w.to_list() == list(range(80))
    w2 = rseq.SeqWriter(rseq.empty(256), rid=1)
    for i in range(80):
        w2.insert_at(0, i)
    assert w2.to_list() == list(range(79, -1, -1))


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_seqwriter_from_gc_wrapper_is_floor_aware():
    """Advisor round 2: constructing a SeqWriter from the tomb_gc.Gc
    wrapper must resume ABOVE the floor — after GC collected a writer's
    highest-seq rows, the table max understates the used range, and
    re-minting a covered (rid, seq) would be join-suppressed."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.models import tomb_gc
    from crdt_tpu.parallel import swarm

    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for i in range(6):
        w.append(i)
    for _ in range(3):
        w.delete_at(3)  # tombstone the three highest-seq rows
    g = tomb_gc.wrap(w.state, 2)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), g, g)
    sw = tomb_gc.gc_round(swarm.make(stacked), rseq.GC_ADAPTER,
                          rseq.empty(CAP))
    g2 = jax.tree.map(lambda x: x[0], sw.state)
    assert int(jnp.asarray(g2.floor)[0]) == 5  # seqs 3..5 collected
    # plain-RSeq resume would re-mint seq 3 (table max is 2); Gc-aware
    # resume starts at 6 = tomb_gc.next_seq
    assert rseq.SeqWriter(g2.inner, rid=0)._seq == 3
    w2 = rseq.SeqWriter(g2, rid=0)
    assert w2._seq == tomb_gc.next_seq(g2, rseq.GC_ADAPTER, 0) == 6
    w2.append(99)  # survives a join against the converged fleet
    healed = tomb_gc.join(g2.replace(inner=w2.state), g2, rseq.GC_ADAPTER)
    assert rseq.to_list(healed.inner) == [0, 1, 2, 99]
