"""Tracing/profiling hooks actually produce traces (SURVEY.md §5: the
reference has no observability at all; here jax.profiler is wired through
utils.tracing and must work end to end on any backend)."""
import pathlib

import jax
import jax.numpy as jnp

from crdt_tpu.models import gcounter
from crdt_tpu.parallel import swarm
from crdt_tpu.utils import tracing


def test_trace_to_captures_profile(tmp_path):
    logdir = tmp_path / "trace"
    s = swarm.make(gcounter.zero(8, batch=(64,)))
    with tracing.trace_to(str(logdir)):
        with tracing.trace_region("converge"):
            out = swarm.converge(
                s, gcounter.join, gcounter.zero(8)
            )
            jax.block_until_ready(out.state.counts)
    produced = list(pathlib.Path(logdir).rglob("*"))
    assert any(p.is_file() for p in produced), "no trace files written"


def test_trace_region_is_transparent():
    with tracing.trace_region("noop"):
        x = jnp.arange(4).sum()
    assert int(x) == 6
