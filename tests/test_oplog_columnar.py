"""Columnar OpLog fast path vs the generic row-major path (interpret mode
on CPU; the Mosaic path is A/B-benched on hardware in
benches/bench_oplog_columnar.py).  Ground truth: vmapped oplog.merge /
swarm.converge over the same stacked states."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crdt_tpu.models import oplog, oplog_columnar as oc
from crdt_tpu.ops import joins
from crdt_tpu.parallel import swarm
from crdt_tpu.utils.constants import SENTINEL_PY

BITS = (4, 22, 5)  # 16 writers x 4M seqs x 32 keys


def _op_pool(rng, n, n_writers=8, n_keys=16):
    """Unique (ts, rid, seq) identities with colliding ts values."""
    ids = rng.choice(n * 4, size=n, replace=False)
    return {
        "ts": (ids // 16).astype(np.int32),  # collisions on purpose
        "rid": rng.integers(0, n_writers, n).astype(np.int32),
        "seq": ids.astype(np.int32),
        "key": rng.integers(0, n_keys, n).astype(np.int32),
        "val": rng.integers(-20, 20, n).astype(np.int32),
        "payload": rng.integers(0, 1000, n).astype(np.int32),
        "is_num": rng.integers(0, 2, n).astype(bool),
    }


def _random_batch(rng, r, c, pool):
    """[R, C] stacked OpLog: each replica holds a random subset of the pool
    (so cross-replica duplicates are plentiful)."""
    n = len(pool["ts"])
    logs = []
    for _ in range(r):
        take = np.nonzero(rng.random(n) < rng.random())[0][:c]  # varied fill
        ops = {k: jnp.asarray(v[take]) for k, v in pool.items()}
        logs.append(oplog.from_ops(c, ops))
    return jax.tree.map(lambda *xs: jnp.stack(xs), *logs)


def _assert_logs_equal(a: oplog.OpLog, b: oplog.OpLog):
    for f in ("ts", "rid", "seq", "key", "val", "payload", "is_num"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


def test_stack_unstack_roundtrip():
    rng = np.random.default_rng(0)
    batch = _random_batch(rng, 6, 32, _op_pool(rng, 40))
    col = oc.stack(batch, bits=BITS)
    _assert_logs_equal(oc.unstack(col), batch)


def test_stack_rejects_out_of_budget_fields():
    rng = np.random.default_rng(1)
    pool = _op_pool(rng, 10)
    pool["key"][:] = 1 << 6  # exceeds the 5-bit key budget
    batch = _random_batch(rng, 2, 16, pool)
    with pytest.raises(ValueError, match="key range"):
        oc.stack(batch, bits=BITS)


def test_fit_bits():
    bits = oc.fit_bits(n_writers=5, n_keys=62)
    assert bits[0] == 3 and bits[2] == 6 and sum(bits) == 31
    with pytest.raises(ValueError):
        oc.check_bits((16, 16, 8))


@pytest.mark.parametrize("c", [16, 64])
def test_columnar_merge_matches_rowmajor(c):
    rng = np.random.default_rng(c)
    pool = _op_pool(rng, c)
    a = _random_batch(rng, 8, c, pool)
    b = _random_batch(rng, 8, c, pool)
    merged, nu = oc.merge_checked(
        oc.stack(a, bits=BITS), oc.stack(b, bits=BITS), interpret=True
    )
    want, want_nu = jax.vmap(oplog.merge_checked)(a, b)
    _assert_logs_equal(oc.unstack(merged), want)
    np.testing.assert_array_equal(np.asarray(nu), np.asarray(want_nu))


def test_columnar_merge_overflow_detected():
    rng = np.random.default_rng(7)
    c = 16
    # two disjoint pools whose union exceeds capacity
    pa, pb = _op_pool(rng, c), _op_pool(rng, c)
    pb["seq"] += 1000
    a = [oplog.from_ops(c, {k: jnp.asarray(v) for k, v in pa.items()})]
    b = [oplog.from_ops(c, {k: jnp.asarray(v) for k, v in pb.items()})]
    a = jax.tree.map(lambda *xs: jnp.stack(xs), *a)
    b = jax.tree.map(lambda *xs: jnp.stack(xs), *b)
    merged, nu = oc.merge_checked(
        oc.stack(a, bits=BITS), oc.stack(b, bits=BITS), interpret=True
    )
    assert int(nu[0]) == 2 * c > merged.capacity
    want, _ = jax.vmap(oplog.merge_checked)(a, b)
    _assert_logs_equal(oc.unstack(merged), want)


@pytest.mark.parametrize("r", [4, 8, 11])
def test_columnar_converge_matches_swarm(r):
    rng = np.random.default_rng(r)
    c = 32
    batch = _random_batch(rng, r, c, _op_pool(rng, 24))
    got, max_nu = oc.converge_checked(oc.stack(batch, bits=BITS), interpret=True)
    s = swarm.converge(
        swarm.make(batch), joins.batched(oplog.merge), oplog.empty(c)
    )
    _assert_logs_equal(oc.unstack(got), s.state)
    assert int(max_nu) <= c


def test_columnar_converge_respects_alive_mask():
    rng = np.random.default_rng(42)
    c, r = 32, 8
    batch = _random_batch(rng, r, c, _op_pool(rng, 24))
    alive = jnp.asarray(rng.integers(0, 2, r).astype(bool).tolist())
    alive = alive.at[0].set(True)  # at least one alive
    got = oc.converge(oc.stack(batch, bits=BITS), alive=alive, interpret=True)
    s = swarm.converge(
        swarm.make(batch, alive), joins.batched(oplog.merge), oplog.empty(c)
    )
    _assert_logs_equal(oc.unstack(got), s.state)


def test_columnar_gossip_round_matches_swarm():
    rng = np.random.default_rng(3)
    c, r = 32, 8
    batch = _random_batch(rng, r, c, _op_pool(rng, 24))
    alive = jnp.asarray([True, False, True, True, True, False, True, True])
    peers = jnp.asarray(rng.integers(0, r, r).astype(np.int32))
    got = oc.gossip_round(
        oc.stack(batch, bits=BITS), peers, alive=alive, interpret=True
    )
    s = swarm.gossip_round(
        swarm.make(batch, alive), peers, joins.batched(oplog.merge)
    )
    _assert_logs_equal(oc.unstack(got), s.state)


def test_columnar_rebuild_matches_rowmajor():
    rng = np.random.default_rng(5)
    c, n_keys = 32, 32
    batch = _random_batch(rng, 4, c, _op_pool(rng, 24, n_keys=n_keys))
    kv = oc.rebuild(oc.stack(batch, bits=BITS), n_keys)
    want = jax.vmap(lambda lg: oplog.rebuild(lg, n_keys))(batch)
    for f in ("present", "is_num", "num", "num_count", "payload"):
        np.testing.assert_array_equal(
            np.asarray(getattr(kv, f)), np.asarray(getattr(want, f)), err_msg=f
        )


def test_sharded_converge_matches_single_device():
    """Columnar convergence with the lane axis sharded over the 8-device
    virtual CPU mesh must equal the single-device converge (and the
    generic swarm path), dead lanes included."""
    from crdt_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(11)
    c, r = 32, 16  # 2 lanes per device
    batch = _random_batch(rng, r, c, _op_pool(rng, 24))
    alive = jnp.asarray([True] * 14 + [False, True])
    col = oc.stack(batch, bits=BITS)
    m = mesh_lib.make_mesh(8)
    step = oc.sharded_converge(m, bits=BITS)  # interpret: auto (cpu)
    sharded_col = jax.device_put(
        col,
        jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec(None, "replica")),
    )
    got, max_nu = step(sharded_col, alive)
    want = oc.converge(col, alive=alive, interpret=True)
    _assert_logs_equal(oc.unstack(got), oc.unstack(want))
    assert int(max_nu) <= c
    s = swarm.converge(
        swarm.make(batch, alive), joins.batched(oplog.merge), oplog.empty(c)
    )
    _assert_logs_equal(oc.unstack(got), s.state)


def test_payload_sign_bit_carries_is_num():
    """pay plane = payload | is_num<<31 must round-trip both fields."""
    rng = np.random.default_rng(9)
    batch = _random_batch(rng, 3, 16, _op_pool(rng, 12))
    col = oc.stack(batch, bits=BITS)
    back = oc.unstack(col)
    valid = np.asarray(batch.ts) != SENTINEL_PY
    np.testing.assert_array_equal(
        np.asarray(back.is_num)[valid], np.asarray(batch.is_num)[valid]
    )
    np.testing.assert_array_equal(
        np.asarray(back.payload)[valid], np.asarray(batch.payload)[valid]
    )
