"""Unified telemetry layer tests (crdt_tpu.obs): the Prometheus
exposition, the mergeable histogram's lattice laws, cross-node trace
propagation, the JSONL event log, and the Metrics shim regressions
(observe() double-count, windowed rate, atomic snapshot).

The histogram merge tests mirror tests/test_lattice_laws.py: a mergeable
histogram is itself a (grow-only) join-semilattice element under
elementwise add, so fleet-wide folds must be order-insensitive.
"""
from __future__ import annotations

import json
import random
import threading
import urllib.request

import pytest

from crdt_tpu.obs.events import EventLog, read_jsonl
from crdt_tpu.obs.registry import (
    N_BUCKETS,
    NULL_REGISTRY,
    Histogram,
    MetricsRegistry,
    bucket_index,
)
from crdt_tpu.obs.trace import TRACE_HEADER, current_trace, mint_trace_id, span
from crdt_tpu.utils.metrics import Metrics


def _rand_hist(rng: random.Random, n: int) -> Histogram:
    h = Histogram()
    for _ in range(n):
        # spread over ~the full bucket range, us .. minutes
        h.observe(rng.uniform(0, 1) * 10 ** rng.randint(-6, 2))
    return h


# ---------------------------------------------------------------- histogram


def test_histogram_merge_associative_commutative():
    """Property-style over random fills: merge is associative and
    commutative (so per-node histograms fold fleet-wide in any order),
    and the empty histogram is the identity."""
    rng = random.Random(0xC4D7)
    for trial in range(50):
        a = _rand_hist(rng, rng.randint(0, 40))
        b = _rand_hist(rng, rng.randint(0, 40))
        c = _rand_hist(rng, rng.randint(0, 40))
        assert a.merge(b) == b.merge(a), trial
        assert a.merge(b).merge(c) == a.merge(b.merge(c)), trial
        assert a.merge(Histogram()) == a, trial
        merged = a.merge(b)
        assert merged.count == a.count + b.count
        assert sum(merged.buckets) == merged.count


def test_histogram_merge_does_not_alias():
    a, b = Histogram(), Histogram()
    a.observe(0.01)
    out = a.merge(b)
    out.observe(0.01)
    assert a.count == 1 and b.count == 0  # merge returned a fresh histogram


def test_bucket_index_monotone_and_bounded():
    prev = 0
    for v in (1e-9, 1e-6, 1e-3, 0.1, 1.0, 60.0, 1e3, 1e9):
        i = bucket_index(v)
        assert 0 <= i < N_BUCKETS
        assert i >= prev
        prev = i
    assert bucket_index(1e-12) == 0
    assert bucket_index(1e12) == N_BUCKETS - 1


def test_histogram_quantile():
    h = Histogram()
    assert h.quantile(0.5) != h.quantile(0.5)  # NaN when empty
    for _ in range(100):
        h.observe(0.010)  # ~10ms
    q = h.quantile(0.5)
    # log2 buckets: estimate is the bucket upper bound, within one octave
    assert 0.010 <= q <= 0.020


def test_histogram_quantile_edges():
    import math

    h = Histogram()
    # empty: every q is NaN, including the edges
    for q in (0.0, 0.5, 1.0, -1.0, 2.0):
        assert math.isnan(h.quantile(q))
    h.observe(0.010)
    h.observe(100.0)
    lo, hi = h.quantile(0.0), h.quantile(1.0)
    # q<=0 clamps to the first occupied bucket, q>=1 to the last — both
    # finite (q=1 used to fall through to +Inf on ceil(1*count) == count
    # landing in the +Inf cumulative check)
    assert 0.010 <= lo <= 0.020
    assert 100.0 <= hi <= 256.0 and math.isfinite(hi)
    assert h.quantile(-0.5) == lo and h.quantile(2.0) == hi
    # single observation: every q names its bucket
    h1 = Histogram()
    h1.observe(0.5)
    assert h1.quantile(0.0) == h1.quantile(0.5) == h1.quantile(1.0) == 0.5
    # an overflow (+Inf bucket) observation keeps q=1 at +Inf honestly
    h2 = Histogram()
    h2.observe(2.0 ** 11)
    assert h2.quantile(1.0) == float("inf")


# ----------------------------------------------------------------- registry


def test_registry_counters_gauges_labels():
    r = MetricsRegistry()
    r.inc("pulls")
    r.inc("pulls", 2)
    r.inc("pulls", peer="n1")
    r.set_gauge("lag", 7.5, node="0")
    assert r.counter_value("pulls") == 3
    assert r.counter_value("pulls", peer="n1") == 1
    assert r.counter_value("absent") == 0
    assert r.gauge_value("lag", node="0") == 7.5
    assert r.gauge_value("lag") is None


def test_registry_snapshot_shape():
    r = MetricsRegistry()
    r.inc("gossip_rounds")
    r.observe("merge", 0.004)
    r.set_gauge("alive", 1, node="2")
    snap = r.snapshot()
    assert snap["gossip_rounds"] == 1
    assert snap["merge_count"] == 1
    assert snap["merge_p50_ms"] > 0
    assert snap['alive{node="2"}'] == 1


def test_snapshot_atomic_under_concurrent_writers():
    """snapshot() must be one consistent copy while writers hammer the
    registry (the old Metrics.snapshot iterated reservoirs outside the
    lock).  Counters observed across snapshots must be nondecreasing and
    no snapshot may raise."""
    m = Metrics()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            m.inc("w")
            m.observe("lat", 0.001)

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        prev_w, prev_n = -1.0, -1
        for _ in range(200):
            snap = m.snapshot()
            w, n = snap.get("w", 0.0), snap.get("lat_count", 0)
            assert w >= prev_w and n >= prev_n
            prev_w, prev_n = w, n
    finally:
        stop.set()
        for t in threads:
            t.join()


def test_null_registry_is_inert():
    NULL_REGISTRY.inc("x")
    NULL_REGISTRY.observe("x", 1.0)
    NULL_REGISTRY.set_gauge("x", 1.0)
    assert NULL_REGISTRY.counter_value("x") == 0
    assert NULL_REGISTRY.histogram("x") is None
    m = Metrics(registry=NULL_REGISTRY)
    m.inc("y")
    with m.timer("t"):
        pass
    assert m.snapshot() == {}


# -------------------------------------------------------- Prometheus text

# one exposition line: name{labels}? value
import re  # noqa: E402

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})?'
    r" [0-9eE+.inf-]+$"
)


def _check_prometheus(text: str) -> int:
    """Validate 0.0.4 text exposition; returns the number of sample lines."""
    n = 0
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            kind = line.split()[-1]
            assert kind in ("counter", "gauge", "histogram"), line
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        n += 1
    return n


def test_render_prometheus_format():
    r = MetricsRegistry()
    r.inc("gossip_rounds", peer="http://127.0.0.1:1")
    r.set_gauge("convergence_lag_ops", 2.4, node="0")
    for v in (1e-5, 0.002, 0.004, 0.3):
        r.observe("merge", v)
    text = r.render_prometheus()
    assert _check_prometheus(text) >= 3
    assert "# TYPE crdt_gossip_rounds_total counter" in text
    assert "# TYPE crdt_convergence_lag_ops gauge" in text
    assert "# TYPE crdt_merge_seconds histogram" in text
    # histogram invariants: cumulative buckets end at count; sum present
    buckets = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("crdt_merge_seconds_bucket")
    ]
    assert buckets == sorted(buckets)  # cumulative -> nondecreasing
    assert buckets[-1] == 4
    assert 'le="+Inf"' in text
    assert "crdt_merge_seconds_sum" in text
    assert "crdt_merge_seconds_count 4" in text


def test_scrape_callbacks_run_at_collection():
    r = MetricsRegistry()
    r.add_callback(lambda reg: reg.set_gauge("sampled", 42))
    assert "crdt_sampled 42" in r.render_prometheus()
    assert r.snapshot()["sampled"] == 42


# ------------------------------------------------------------ Metrics shim


def test_observe_does_not_double_count():
    """Regression: the old Metrics.observe() also bumped the counter of
    the same name, so 'merge' reported events + durations conflated."""
    m = Metrics()
    m.inc("merge_events", 3)
    for _ in range(5):
        m.observe("merge", 0.002)
    snap = m.snapshot()
    assert snap["merge_count"] == 5
    assert snap["merge_events"] == 3
    assert "merge" not in snap  # no phantom counter from observe()
    assert "merge" not in m._counts
    assert m.registry.counter_value("merge") == 0


def test_counts_backcompat_view():
    m = Metrics()
    m.inc("seq_collect_behind")
    m.registry.inc("labeled", peer="x")  # labeled series not in _counts
    assert m._counts == {"seq_collect_behind": 1}


def test_timer_and_quantiles():
    m = Metrics()
    with m.timer("merge"):
        pass
    assert m.snapshot()["merge_count"] == 1
    assert m.p50("merge") > 0
    assert m.quantile("absent", 0.5) != m.quantile("absent", 0.5)  # NaN


def test_rate_lifetime_and_windowed():
    m = Metrics()
    for _ in range(10):
        m.inc("ops")
    assert m.rate("ops") > 0
    assert m.rate("absent") == 0
    # a window covering the whole lifetime sees every event
    full = m.rate("ops", window=60.0)
    assert full == pytest.approx(m.rate("ops"), rel=0.5)
    assert m.rate("absent", window=60.0) == 0


# ------------------------------------------------------------- event logs


def test_event_log_ring_and_file(tmp_path):
    p = str(tmp_path / "events.jsonl")
    log = EventLog(node="7", path=p, capacity=4)
    for i in range(6):
        log.emit("tick", i=i)
    log.emit("pull_merge", trace="abc", fresh=2)
    assert len(log) == 4  # ring bounded
    assert log.tail(1)[0]["event"] == "pull_merge"
    assert log.find(trace="abc")[0]["fresh"] == 2
    assert log.find(event="tick", trace="abc") == []
    log.close()
    recs = read_jsonl(p)
    assert len(recs) == 7  # the file keeps everything
    assert recs[-1]["node"] == "7" and recs[-1]["trace"] == "abc"
    assert all("ts_ms" in r for r in recs)


def test_read_jsonl_tolerates_torn_tail(tmp_path):
    """A SIGKILL can tear the final line mid-write; everything before the
    tear must still parse (the crash soak's black-box reader)."""
    p = tmp_path / "events.jsonl"
    with open(p, "w") as fh:
        fh.write(json.dumps({"event": "boot"}) + "\n")
        fh.write(json.dumps({"event": "pull_merge"}) + "\n")
        fh.write('{"event": "pull_m')  # torn
    recs = read_jsonl(str(p))
    assert [r["event"] for r in recs] == ["boot", "pull_merge"]
    assert read_jsonl(str(tmp_path / "absent.jsonl")) == []


# ---------------------------------------------------------------- tracing


def test_mint_trace_id_unique_and_rid_tagged():
    ids = {mint_trace_id(3) for _ in range(100)}
    assert len(ids) == 100
    assert all(i.startswith("3-") for i in ids)


def test_span_binds_current_trace():
    assert current_trace() is None
    with span("crdt.test", "tid-1") as tid:
        assert tid == "tid-1" and current_trace() == "tid-1"
        with span("crdt.inner") as inner:  # inherits the enclosing trace
            assert inner == "tid-1"
    assert current_trace() is None


# ------------------------------------------- end-to-end over real sockets


@pytest.fixture
def traced_pair(tmp_path):
    """Two standalone NodeHosts with JSONL event logs, peered (mirrors
    tests/test_net.py's pair, plus the black-box files)."""
    from crdt_tpu.api.net import NodeHost, RemotePeer

    a = NodeHost(rid=0, peers=[], event_log=str(tmp_path / "a.jsonl"))
    b = NodeHost(rid=1, peers=[], event_log=str(tmp_path / "b.jsonl"))
    a.agent.peers = [RemotePeer(b.url)]
    b.agent.peers = [RemotePeer(a.url)]
    for h in (a, b):
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()
    yield a, b
    for h in (a, b):
        h._server.shutdown()
        h._server.server_close()


def test_trace_survives_two_node_pull(traced_pair, tmp_path):
    """One gossip round = one trace ID on BOTH ends of the wire: the
    puller's pull_merge event and the server's gossip_serve event carry
    the same ID, in memory and in both JSONL files."""
    from crdt_tpu.api.net import RemotePeer

    a, b = traced_pair
    RemotePeer(a.url).add_command({"x": "5"})
    assert b.agent.gossip_once()  # b pulls from a

    merges = b.node.events.find(event="pull_merge")
    assert merges, [e["event"] for e in b.node.events.tail()]
    tid = merges[-1]["trace"]
    assert tid.startswith("1-")  # minted by the puller (rid=1)
    serves = a.node.events.find(event="gossip_serve", trace=tid)
    assert serves and serves[-1]["delta"] is True

    # and the same ID is greppable across both black-box files
    for fname, event in (("a.jsonl", "gossip_serve"), ("b.jsonl", "pull_merge")):
        recs = read_jsonl(str(tmp_path / fname))
        assert any(
            r.get("trace") == tid and r["event"] == event for r in recs
        ), (fname, tid)
    # boot events were flushed at construction time on both hosts
    assert read_jsonl(str(tmp_path / "a.jsonl"))[0]["event"] == "boot"


def test_metrics_endpoint_prometheus(traced_pair):
    """GET /metrics is valid Prometheus text with ≥10 series including
    the gossip counters and the scrape-time lattice health gauges."""
    from crdt_tpu.api.net import RemotePeer

    a, b = traced_pair
    RemotePeer(a.url).add_command({"x": "1"})
    RemotePeer(a.url).add_command({"y": "2"})
    assert b.agent.gossip_once()

    with urllib.request.urlopen(b.url + "/metrics", timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in resp.headers["Content-Type"]
        text = resp.read().decode()

    n_series = _check_prometheus(text)
    assert n_series >= 10, text
    assert "crdt_net_gossip_rounds_total" in text
    assert "crdt_net_gossip_payload_ops_total" in text
    assert "crdt_ops_ingested_total" in text
    # lattice health gauges sampled at scrape time
    assert 'crdt_node_alive{node="1"} 1' in text
    assert 'crdt_vv_ops_known{node="1"}' in text
    assert 'crdt_peer_ops_behind{node="1",peer=' in text
    assert 'crdt_convergence_lag_ops{node="1"}' in text
    assert "crdt_merge_seconds_bucket" in text
