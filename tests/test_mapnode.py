"""MapNode: the PN-composition map across the process boundary (round-5)
— wire merges pinned bit-exactly to the device OR-Map lattice
(ormap_gc.join on device_state views), reset-wins epochs, and the
stale-snapshot-vs-reset absorption rule."""
import json

import numpy as np

from crdt_tpu.api.mapnode import EPOCH_KEY, MapNode, map_barrier_ready


def pull(dst: MapNode, src: MapNode) -> int:
    return dst.receive(src.gossip_payload(since=dst.version_vector()))


def sync(a: MapNode, b: MapNode) -> None:
    for _ in range(2):
        pull(a, b)
        pull(b, a)


def assert_device_equal(x, y):
    for lx, ly in zip(
        __import__("jax").tree.leaves(x), __import__("jax").tree.leaves(y)
    ):
        np.testing.assert_array_equal(np.asarray(lx), np.asarray(ly))


def test_basic_pn_semantics_and_convergence():
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("x", 5)
    a.upd("x", -2)
    b.upd("x", 10)
    b.upd("y", -7)
    sync(a, b)
    assert a.items() == {"x": 13, "y": -7}
    assert b.items() == a.items()
    # wire-merged planes == the device lattice join of the divergent states
    a2, b2 = MapNode(rid=0), MapNode(rid=1)
    a2.upd("x", 5)
    a2.upd("x", -2)
    b2.upd("x", 10)
    b2.upd("y", -7)
    from crdt_tpu.models import ormap_gc, pncounter
    import jax

    da, db = a2.device_state(), b2.device_state()
    want = ormap_gc.join(
        da, db, jax.vmap(pncounter.join)
    )
    sync(a2, b2)
    assert_device_equal(a2.device_state(), want)


def test_observed_remove_is_add_wins():
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("k", 3)
    sync(a, b)
    # concurrent: b updates while a removes (a has not seen b's token)
    b.upd("k", 4)
    assert a.rem("k") is not None
    sync(a, b)
    # the unseen token keeps the key alive; value keeps the full history
    assert a.value("k") == 7
    assert b.value("k") == 7


def test_remove_then_reset_barrier():
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("gone", 9)
    a.upd("kept", 1)
    sync(a, b)
    b.rem("gone")
    sync(a, b)
    assert a.items() == {"kept": 1}
    # full-fleet precondition holds (a dominates b after sync)
    assert map_barrier_ready(a, [b.version_vector()])
    epochs = a.mint_reset()
    assert epochs == {"gone": 1}
    # b learns the reset via ordinary gossip (epochs ride the payload)
    pull(b, a)
    assert b.epochs() == {"gone": 1}
    # re-add starts FRESH (no accumulated history resurfaces)
    b.upd("gone", 2)
    sync(a, b)
    assert a.value("gone") == 2
    assert b.value("gone") == 2
    # records for the reset key's old ops are pruned everywhere (bounded)
    for n in (a, b):
        for op in n._ops.values():
            key = op.get("upd") or op.get("rem")
            assert not (key == "gone" and op.get("e", 0) < 1)


def test_reset_wins_against_stale_update():
    """An update minted on a state that had not yet learned an agreed
    reset loses to it (ormap_gc's reset-wins rule, op-wise)."""
    a, b, c = MapNode(rid=0), MapNode(rid=1), MapNode(rid=2)
    a.upd("k", 5)
    sync(a, b)
    sync(a, c)
    b.rem("k")
    sync(a, b)
    # c is partitioned; fleet = {a, b} agrees on the reset
    assert map_barrier_ready(a, [b.version_vector()])
    a.mint_reset()
    pull(b, a)
    # c (old epoch) mints an update — dominated once the epoch arrives
    c.upd("k", 100)
    sync(a, c)
    sync(b, c)
    assert a.value("k") is None  # reset key, stale update voided
    assert b.value("k") is None
    assert c.value("k") is None
    assert c.epochs() == {"k": 1}


def test_barrier_not_ready_when_member_unreachable_or_behind():
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("x", 1)
    assert not map_barrier_ready(a, [None])  # unreachable member
    b.upd("y", 2)  # b holds an op a has not folded
    assert not map_barrier_ready(a, [b.version_vector()])
    pull(a, b)
    assert map_barrier_ready(a, [b.version_vector()])


def test_snapshot_roundtrip_and_stale_restore_absorbed():
    """The crashsoak hard case in miniature: a restore from a PRE-barrier
    snapshot (old epoch, dominated ops) must be absorbed on its first
    pull, and its post-restore stale-epoch update resolves reset-wins."""
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("k", 5)
    a.upd("stay", 1)
    sync(a, b)
    snap = json.loads(json.dumps(b.to_snapshot()))  # pre-barrier snapshot
    b.rem("k")
    sync(a, b)
    a.mint_reset()
    pull(b, a)
    assert b.epochs() == {"k": 1}
    # b crashes; restores the stale snapshot (epoch 0, k's ops retained)
    b2 = MapNode(rid=1)
    b2.from_snapshot(snap)
    assert b2.value("k") == 5  # stale state resurrected locally...
    b2.upd("k", 50)  # ...and even written to, at the old epoch
    sync(a, b2)
    # absorbed: epoch adopted, stale rows voided, fleet converged
    assert b2.epochs() == {"k": 1}
    assert a.value("k") is None
    assert b2.value("k") is None
    assert a.value("stay") == 1 and b2.value("stay") == 1
    # the restored node's seq counter resumed at the SNAPSHOT's count —
    # identity reuse against ops minted after the snapshot is the
    # incarnation-rid machinery's job (checkpoint.bump_incarnation; the
    # crashsoak exercises it across real process boundaries)
    ident = b2.upd("fresh", 1)
    assert ident == (1, 1)


def test_delta_payload_carries_epochs_and_is_always_valid():
    a, b = MapNode(rid=0), MapNode(rid=1)
    a.upd("k", 1)
    sync(a, b)
    b.rem("k")
    sync(a, b)
    a.mint_reset()
    p = a.gossip_payload(since=b.version_vector())
    assert p[EPOCH_KEY] == {"k": 1}
    # ops dominated by the reset were pruned from the sender — the delta
    # is just the epoch section, and receiving it converges b
    b.receive(p)
    assert b.epochs() == {"k": 1}
    assert b.items() == {}


def test_vv_reconverges_across_reset_pruning():
    """The crashsoak-found bug (round-5): a reset prunes dominated ops
    from every holder, so a replica that never received them could keep
    a permanent vv hole — the payload's vv section must close it."""
    a, b, c = MapNode(rid=0), MapNode(rid=1), MapNode(rid=2)
    a.upd("k", 5)
    sync(a, b)  # c never sees (0, 0)
    b.rem("k")
    sync(a, b)
    assert map_barrier_ready(a, [b.version_vector()])
    a.mint_reset()  # (0,0) and b's remove now pruned everywhere that held them
    pull(b, a)
    # c pulls from a: the voided ops are gone from a's records, but the
    # vv section covers them — c's vv must converge to the fleet's
    pull(c, a)
    assert c.version_vector() == a.version_vector()
    assert c.epochs() == {"k": 1}
    assert c.items() == {}
