"""Columnar RSeq fast path (crdt_tpu.models.rseq_columnar) vs the generic
row-major join — interpret mode on CPU; the compiled Mosaic path is covered
by benches/hw_selftest.py.  Ground truth: vmapped rseq.join_checked over
the same stacked states."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crdt_tpu.models import rseq, rseq_columnar as rc

CAP = 64


def _edited_state(rng, rid_base, base_state=None, n_edits=12, cap=CAP):
    w = rseq.SeqWriter(
        rseq.empty(cap) if base_state is None else base_state, rid=rid_base
    )
    for _ in range(n_edits):
        n = len(w.to_list())
        if n and rng.random() < 0.35:
            w.delete_at(int(rng.integers(0, n)))
        else:
            w.insert_at(int(rng.integers(0, n + 1)), int(rng.integers(0, 500)))
    return w.state


def _swarm(rng, r=4, rid_base=10, base=None, cap=CAP):
    """[R, C, 4D] batched RSeq: concurrent branches off a shared base (so
    cross-replica duplicate keys AND one-sided tombstones are plentiful).

    Writer rids must be globally unique across every state that will ever
    be joined — two writers minting the same (rid, seq) for different
    content would violate the op-identity invariant every join in the
    framework (generic included) is built on."""
    if base is None:
        base = _edited_state(rng, rid_base=0, n_edits=8, cap=cap)
    states = [
        _edited_state(rng, rid_base=rid_base + k, base_state=base, cap=cap)
        for k in range(r)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _assert_rseq_equal(a: rseq.RSeq, b: rseq.RSeq):
    np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(b.elem))
    np.testing.assert_array_equal(
        np.asarray(a.removed), np.asarray(b.removed)
    )


def test_stack_unstack_roundtrip():
    rng = np.random.default_rng(0)
    batch = _swarm(rng)
    col = rc.stack(batch)
    assert col.depth == rseq.DEPTH and col.lanes == 4
    _assert_rseq_equal(rc.unstack(col), batch)


def test_stack_single_state():
    rng = np.random.default_rng(1)
    s = _edited_state(rng, rid_base=3)
    col = rc.stack(s)
    back = rc.unstack(col)
    assert rseq.to_list(jax.tree.map(lambda x: x[0], back)) == rseq.to_list(s)


def test_pack_order_matches_row_order():
    """Packed-word lexicographic order must equal the 4D-column order —
    the whole point of the layout.  The stacked planes must already be
    per-lane sorted because the row-major rows were."""
    rng = np.random.default_rng(2)
    col = rc.stack(_swarm(rng))
    keys = np.asarray(col.keys)  # (3D, C, R)
    for lane in range(keys.shape[2]):
        rows = [tuple(keys[:, i, lane]) for i in range(keys.shape[1])]
        assert rows == sorted(rows), f"lane {lane} not sorted after pack"


@pytest.mark.parametrize("seed", [3, 4, 5])
def test_columnar_merge_matches_generic_join(seed):
    rng = np.random.default_rng(seed)
    base = _edited_state(rng, rid_base=0, n_edits=8)
    a = _swarm(rng, rid_base=10, base=base)
    b = _swarm(rng, rid_base=20, base=base)  # disjoint writers, shared base
    ca, cb = rc.stack(a), rc.stack(b)
    if ca.seq_bits != cb.seq_bits:
        common = min(ca.seq_bits, cb.seq_bits)
        ca, cb = rc.stack(a, seq_bits=common), rc.stack(b, seq_bits=common)
    got, nu = rc.merge_checked(ca, cb, interpret=True)
    want, wnu = jax.vmap(rseq.join_checked)(a, b)
    _assert_rseq_equal(rc.unstack(got), want)
    np.testing.assert_array_equal(np.asarray(nu), np.asarray(wnu))


def test_one_sided_tombstone_survives_the_kernel():
    """The OR-combine-on-punch rule: a removal held by only one side of a
    duplicate key must survive whichever copy the network keeps."""
    rng = np.random.default_rng(6)
    base = _edited_state(rng, rid_base=0, n_edits=10)
    wa = rseq.SeqWriter(base, rid=1)
    wb = rseq.SeqWriter(base, rid=2)
    wa.delete_at(0)          # a tombstones an element b still holds live
    wb.insert_at(0, 999)
    a = jax.tree.map(lambda *x: jnp.stack(x), wa.state, wa.state)
    b = jax.tree.map(lambda *x: jnp.stack(x), wb.state, wb.state)
    common = min(rc.stack(a).seq_bits, rc.stack(b).seq_bits)
    got, _ = rc.merge_checked(
        rc.stack(a, seq_bits=common), rc.stack(b, seq_bits=common),
        interpret=True,
    )
    want = rseq.join(wa.state, wb.state)
    lst = rseq.to_list(jax.tree.map(lambda x: x[0], rc.unstack(got)))
    assert lst == rseq.to_list(want)


def test_converge_matches_generic(seed=7):
    rng = np.random.default_rng(seed)
    state = _swarm(rng, r=4)
    col = rc.stack(state)
    conv, max_nu = rc.converge_checked(col, interpret=True)
    # generic ground truth: fold all replicas pairwise
    states = [jax.tree.map(lambda x, _i=i: x[_i], state) for i in range(4)]
    top = states[0]
    for s in states[1:]:
        top = rseq.join(top, s)
    got = rc.unstack(conv)
    for i in range(4):
        one = jax.tree.map(lambda x, _i=i: x[_i], got)
        assert rseq.to_list(one) == rseq.to_list(top)
    assert int(max_nu) <= CAP


def test_converge_respects_alive_mask():
    rng = np.random.default_rng(8)
    state = _swarm(rng, r=4)
    col = rc.stack(state)
    alive = jnp.asarray([True, True, False, True])
    conv = rc.converge(col, alive, interpret=True)
    got = rc.unstack(conv)
    # the dead lane keeps its stale table
    dead = jax.tree.map(lambda x: x[2], got)
    orig = jax.tree.map(lambda x: x[2], state)
    assert rseq.to_list(dead) == rseq.to_list(orig)
    # alive lanes agree with the alive-only LUB (dead contributes nothing)
    states = [jax.tree.map(lambda x, _i=i: x[_i], state) for i in (0, 1, 3)]
    top = states[0]
    for s in states[1:]:
        top = rseq.join(top, s)
    for i in (0, 1, 3):
        one = jax.tree.map(lambda x, _i=i: x[_i], got)
        assert rseq.to_list(one) == rseq.to_list(top)


def test_gossip_round_matches_generic():
    rng = np.random.default_rng(9)
    state = _swarm(rng, r=4)
    col = rc.stack(state)
    peers = jnp.asarray([1, 2, 3, 0], jnp.int32)
    got = rc.unstack(rc.gossip_round(col, peers, interpret=True))
    for i, p in enumerate([1, 2, 3, 0]):
        a = jax.tree.map(lambda x, _i=i: x[_i], state)
        b = jax.tree.map(lambda x, _p=p: x[_p], state)
        want = rseq.join(a, b)
        one = jax.tree.map(lambda x, _i=i: x[_i], got)
        assert rseq.to_list(one) == rseq.to_list(want)


def test_overflow_stays_detectable():
    """Two disjoint near-full tables: the true union exceeds capacity and
    n_unique must say so (pre-truncation count)."""
    cap = 16

    def appended(rid, n):
        w = rseq.SeqWriter(rseq.empty(cap), rid=rid)
        for i in range(n):
            w.append(i)
        return w.state

    a = appended(1, 12)
    b = appended(2, 12)  # disjoint writers: union = 24 rows > 16
    ab = jax.tree.map(lambda *x: jnp.stack(x), a, a)
    bb = jax.tree.map(lambda *x: jnp.stack(x), b, b)
    common = min(rc.stack(ab).seq_bits, rc.stack(bb).seq_bits)
    _, nu = rc.merge_checked(
        rc.stack(ab, seq_bits=common), rc.stack(bb, seq_bits=common),
        interpret=True,
    )
    _, wnu = rseq.join_checked(a, b)
    assert int(nu[0]) == int(wnu) > cap


def test_stack_rejects_out_of_budget_seq():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=1)
    for i in range(6):
        w.append(i)  # seqs 0..5 — a 2-bit seq field cannot hold 5
    batch = jax.tree.map(lambda x: x[None], w.state)
    with pytest.raises(ValueError, match="exceeds the"):
        rc.stack(batch, seq_bits=2)


def test_merge_rejects_mismatched_layouts():
    rng = np.random.default_rng(12)
    state = _swarm(rng)
    ca = rc.stack(state, seq_bits=20)
    cb = rc.stack(state, seq_bits=21)
    with pytest.raises(ValueError, match="pack layouts"):
        rc.merge_checked(ca, cb)


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_sharded_converge_matches_single_device():
    """The lexN kernel under shard_map over the 8-device virtual mesh must
    agree with the single-device converge (and with the generic path via
    test_converge_matches_generic's oracle)."""
    from crdt_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(20)
    state = _swarm(rng, r=8)
    col = rc.stack(state)
    m = mesh_lib.make_mesh(8)
    step = rc.sharded_converge(m, seq_bits=col.seq_bits)
    alive = jnp.asarray([True] * 6 + [False, True])
    out, max_nu = step(col, alive)
    want, wnu = rc.converge_checked(col, alive, interpret=True)
    np.testing.assert_array_equal(np.asarray(out.keys), np.asarray(want.keys))
    np.testing.assert_array_equal(np.asarray(out.elem), np.asarray(want.elem))
    np.testing.assert_array_equal(
        np.asarray(out.removed), np.asarray(want.removed)
    )
    assert int(max_nu) == int(wnu)


def test_plan_selects_columnar_and_falls_back_loudly():
    from crdt_tpu.models.oplog_engine import EngineFallback

    rng = np.random.default_rng(21)
    state = _swarm(rng)
    col, reason = rc.plan(state)
    assert col is not None and reason is None
    # non-pow2 capacity cannot ride the bitonic network... capacity is
    # checked at merge time; the plan-level budget failure is identity
    # overflow: force it with a pinned too-narrow split
    with pytest.warns(EngineFallback, match="exceeds the"):
        col2, reason2 = rc.plan(state, seq_bits=1)
    assert col2 is None and "exceeds the" in reason2
