"""OpLog store unit tests: append/merge/rebuild semantics (the reference's
write path main.go:173-215, merge main.go:35-100).  Bit-exact parity against
the quirk-togglable oracle lives in tests/test_parity.py."""
import numpy as np

from crdt_tpu.models import oplog
from tests import helpers
from tests.helpers import tree_equal


def _ops(rows):
    """rows: list of (ts, rid, seq, key, val, is_num); payload mirrors val."""
    cols = list(zip(*rows))
    names = ["ts", "rid", "seq", "key", "val", "is_num"]
    d = {
        n: np.asarray(c, bool if n == "is_num" else np.int32)
        for n, c in zip(names, cols)
    }
    d["payload"] = d["val"].copy()
    return d


def test_append_and_rebuild_counter():
    log = oplog.empty(16)
    log = oplog.append_batch(
        log, _ops([(1, 0, 0, 0, 5, True), (2, 0, 1, 0, -3, True), (3, 1, 0, 1, 7, True)])
    )
    kv = oplog.rebuild(log, n_keys=3)
    assert list(np.asarray(kv.present)) == [True, True, False]
    assert list(np.asarray(kv.num)) == [2, 7, 0]
    assert int(oplog.size(log)) == 3


def test_rebuild_lww_for_non_numeric_newest():
    # newest entry for key 0 is non-numeric -> LWW payload; older numeric
    # deltas are skipped (reference fold: curr fails Atoi, main.go:87-90).
    log = oplog.empty(8)
    log = oplog.append_batch(
        log, _ops([(1, 0, 0, 0, 5, True), (9, 1, 0, 0, 42, False)])
    )
    kv = oplog.rebuild(log, n_keys=1)
    assert not bool(kv.is_num[0])
    assert int(kv.payload[0]) == 42


def test_rebuild_numeric_newest_sums_all_numeric():
    # newest numeric -> counter mode: sum of ALL numeric entries, non-numeric
    # interlopers skipped (main.go:91-96).
    log = oplog.empty(8)
    log = oplog.append_batch(
        log,
        _ops([(1, 0, 0, 0, 5, True), (2, 0, 1, 0, 99, False), (3, 0, 2, 0, -2, True)]),
    )
    kv = oplog.rebuild(log, n_keys=1)
    assert bool(kv.is_num[0])
    assert int(kv.num[0]) == 3


def test_merge_adopts_all_remote_no_tail_drop():
    # Remote ops newer than everything local are adopted in ONE merge —
    # the fix for quirk §0.1.3 (reference loop ends at the shorter log).
    local = oplog.from_ops(16, _ops([(1, 0, 0, 0, 1, True)]))
    remote = oplog.from_ops(16, _ops([(10, 1, 0, 0, 2, True), (20, 1, 1, 0, 3, True)]))
    merged = oplog.merge(local, remote)
    assert int(oplog.size(merged)) == 3
    assert int(oplog.rebuild(merged, 1).num[0]) == 6


def test_same_millisecond_ops_do_not_collide():
    # Two ops in the same ms from different writers both survive — the fix
    # for quirk §0.1.2 (reference keys the log by UnixMilli alone).
    a = oplog.from_ops(16, _ops([(5, 0, 0, 0, 1, True)]))
    b = oplog.from_ops(16, _ops([(5, 1, 0, 0, 10, True)]))
    merged = oplog.merge(a, b)
    assert int(oplog.size(merged)) == 2
    assert int(oplog.rebuild(merged, 1).num[0]) == 11


def test_multi_key_command_applies_fully():
    # A multi-key command is several rows sharing (ts, rid, seq) — all keys
    # apply (fix for quirk §0.1.4's early return).
    log = oplog.from_ops(
        16, _ops([(1, 0, 0, 0, 4, True), (1, 0, 0, 1, 6, True), (1, 0, 0, 2, 8, True)])
    )
    kv = oplog.rebuild(log, n_keys=3)
    assert list(np.asarray(kv.num)) == [4, 6, 8]


def test_merge_convergence_random():
    rng = np.random.default_rng(11)
    for _ in range(10):
        logs = helpers.rand_oplog_family(rng, n_logs=4, capacity=64, pool=24, take=12)
        # all-pairs gossip in two different orders reaches the same state
        x = logs[0]
        for l in logs[1:]:
            x = oplog.merge(x, l)
        y = logs[-1]
        for l in reversed(logs[:-1]):
            y = oplog.merge(y, l)
        assert tree_equal(x, y)
        assert tree_equal(oplog.rebuild(x, 6), oplog.rebuild(y, 6))
