"""CI sweep of the sequence-workload soak (RSeq allocator + tombstone GC
under adversarial concurrent editing; long mode via CRDT_LONG/--long)."""
import pytest

from crdt_tpu.harness.seq_soak import SeqSoakRunner


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
@pytest.mark.parametrize("seed", [0, 1])
def test_seq_soak_short(seed):
    report = SeqSoakRunner(n=3, seed=seed, capacity=256).run(120)
    assert report.steps == 120
    assert report.inserts > 0 and report.joins > 0


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_seq_soak_exercises_gc_and_restarts():
    """A delete-heavy schedule with frequent barriers and restarts: rows
    must be reclaimed and restarted cursors must keep editing safely."""
    # every probability named so the distribution sums to 1.0 exactly —
    # an unnamed default would silently dilute the barrier weight
    r = SeqSoakRunner(
        n=3, seed=5, capacity=256, p_insert=0.27, p_run=0.03, p_delete=0.22,
        p_join=0.2, p_kill=0.0, p_revive=0.0, p_restart=0.1, p_barrier=0.18,
    ).run(300)
    assert r.barriers >= 3
    assert r.restarts >= 3
    assert r.rows_reclaimed > 0
    # two replicas may concurrently delete the SAME element, so distinct
    # victims <= delete ops; exact content equality vs the mirror oracle
    # is already asserted inside every step
    assert r.inserts - r.deletes <= r.final_len < r.inserts


def test_seq_soak_long(request):
    import os

    # --long (conftest) or CRDT_LONG both enable it, like the other
    # long-mode suites (tests/test_parity_fuzz.py)
    if not (request.config.getoption("--long") or os.environ.get("CRDT_LONG")):
        pytest.skip("long soak: pytest --long (or CRDT_LONG=1)")
    # engine split: the columnar engine's CPU INTERPRET emulation costs
    # ~10-20x the generic jit path per join at capacity 1024, so all-
    # columnar long seeds run for hours.  Two columnar seeds keep long-
    # mode aging of the default engine (equivalence is pinned bit-exactly
    # by tests/test_rseq_engine.py; on TPU the engine is compiled Mosaic,
    # where the ratio INVERTS — see PERF.md); the remaining seeds stress
    # the allocator/GC schedule on the generic path at full length.
    for seed in range(2):
        SeqSoakRunner(n=4, seed=seed, capacity=512, engine="auto").run(400)
    for seed in range(2, 6):
        SeqSoakRunner(n=4, seed=seed, capacity=1024,
                      engine="generic").run(1000)
