"""crdtflow self-tests: the three PR-17-review lock-leak bugs,
reintroduced verbatim as fixtures, are each flagged by the matching rule
(CRDT212, CRDT210, CRDT212), clean shapes stay clean (`with` blocks,
``_locked`` callees, ``land_all_inline``-style drain helpers, the fixed
incremental builds), and the race-detector bridge maps witnesses to
covering findings.
"""
import textwrap

from crdt_tpu import analysis
from crdt_tpu.analysis import Finding, flow


def _flow_snippet(tmp_path, source, relpath="fixture.py"):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return flow.check_files([p], tmp_path)


def _rules(findings):
    return sorted(f.rule for f in findings)


# ------------------------------------------------ the three PR-17 bugs

def test_pr17_bug1_comprehension_built_lane_list(tmp_path):
    """Bug 1: PendingMerge lanes built in a comprehension — a failure
    mid-build leaks every earlier shard's held node lock (CRDT212)."""
    findings = _flow_snippet(tmp_path, """
        def receive_all(self, payloads):
            pendings = [shard.merge_begin([p])
                        for shard, p in zip(self.shards, payloads)]
            return self.plane.converge(pendings)
    """)
    assert "CRDT212" in _rules(findings)
    (f,) = [f for f in findings if f.rule == "CRDT212"]
    assert "comprehension" in f.message
    assert "_lock" in f.message
    assert f.severity == "error"


def test_pr17_bug2_first_failure_commit_sweep(tmp_path):
    """Bug 2: the commit sweep stops at the first failing lane — locks
    acquired for the later lanes are never released (CRDT210)."""
    findings = _flow_snippet(tmp_path, """
        def converge(self, a, b):
            a._lock.acquire()
            b._lock.acquire()
            total = a.commit_rows()    # first failure aborts the sweep
            total += b.commit_rows()
            a._lock.release()
            b._lock.release()
            return total
    """)
    assert "CRDT210" in _rules(findings)
    assert any("exception path" in f.message for f in findings
               if f.rule == "CRDT210")


def test_pr17_bug3_unresolved_claims_on_converge_error(tmp_path):
    """Bug 3: converge raises after the lanes were claimed — the
    DrainClaims are never resolved/failed and their drain slots (and the
    tickets waiting on them) hang forever (CRDT212)."""
    findings = _flow_snippet(tmp_path, """
        def flush_fused(self, lane, plane, pendings):
            claim = lane.claim()
            plane.converge(pendings)   # raises -> claim leaks
            return claim.resolve([])
    """)
    assert "CRDT212" in _rules(findings)
    (f,) = [f for f in findings if f.rule == "CRDT212"]
    assert "DrainClaim" in f.message and "exception path" in f.message


# ------------------------------------------- the fixed shapes are clean

def test_pr17_fix1_incremental_build_with_landing_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def receive_all(self, shards):
            pendings = []
            try:
                for shard in shards:
                    pendings.append(shard.merge_begin([]))
            except BaseException:
                self.land_all_inline(pendings)
                raise
            return self.plane.converge(pendings)
    """)
    assert findings == []


def test_pr17_fix2_per_lane_try_finally_sweep_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def converge(self, lanes):
            total = 0
            for lane in lanes:
                lane._lock.acquire()
                try:
                    total += lane.commit_rows()
                finally:
                    lane._lock.release()
            return total
    """)
    assert findings == []


def test_pr17_fix3_claim_guarded_by_fail_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def flush_fused(self, lane, plane, pendings):
            claim = lane.claim()
            if claim is None:
                return 0
            try:
                plane.converge(pendings)
            except BaseException as exc:
                return claim.fail(exc)
            return claim.resolve([])
    """)
    assert findings == []


# ------------------------------------------------------------- CRDT210

def test_bare_acquire_with_raising_call_leaks(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def poke(self):
            self._lock.acquire()
            self.refresh()
            self._lock.release()
    """)
    assert "CRDT210" in _rules(findings)


def test_try_finally_release_discharges(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def poke(self):
            self._lock.acquire()
            try:
                self.refresh()
            finally:
                self._lock.release()
    """)
    assert findings == []


def test_with_block_discharges(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def poke(self):
            with self._lock:
                self.refresh()
    """)
    assert findings == []


def test_creator_convention_returns_holding(tmp_path):
    """merge_begin-style creators RETURN holding their lock by contract:
    the normal exit is exempt, but an unguarded raise edge still flags."""
    clean = _flow_snippet(tmp_path, """
        def merge_begin(self, batch):
            self._lock.acquire()
            try:
                self._accept(batch)
                pending = PendingMerge(self)
            except BaseException:
                self._lock.release()
                raise
            return pending
    """)
    assert clean == []
    leaky = _flow_snippet(tmp_path, """
        def merge_begin(self, batch):
            self._lock.acquire()
            self._accept(batch)
            return PendingMerge(self)
    """, relpath="leaky.py")
    assert "CRDT210" in _rules(leaky)


def test_door_lock_recognized_via_threading_registry(tmp_path):
    """``self._adm`` has no 'lock' in its name — it's recognized as a
    lock because __init__ assigns it ``threading.Lock()``."""
    findings = _flow_snippet(tmp_path, """
        import threading

        class Door:
            def __init__(self):
                self._adm = threading.Lock()

            def submit(self):
                self._adm.acquire()
                self.push()
                self._adm.release()
    """)
    assert "CRDT210" in _rules(findings)


def test_locked_callee_convention_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def update(self):
            with self._lock:
                self._bump_locked()

        def _bump_locked(self):
            self.n += 1
    """)
    assert findings == []


# ------------------------------------------------------------- CRDT211

def test_declared_order_violation_node_before_drain(tmp_path):
    """parallel/README.md declares drain (lane) locks strictly before
    node locks — acquiring _drain_lock under _lock is flagged."""
    findings = _flow_snippet(tmp_path, """
        def backwards(self, lane):
            self._lock.acquire()
            try:
                lane._drain_lock.acquire()
                try:
                    self.fold()
                finally:
                    lane._drain_lock.release()
            finally:
                self._lock.release()
    """)
    flagged = [f for f in findings if f.rule == "CRDT211"]
    assert flagged and "declared" in flagged[0].message


def test_declared_order_respected_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def forwards(self, lane):
            lane._drain_lock.acquire()
            try:
                with self._lock:
                    self.fold()
            finally:
                lane._drain_lock.release()
    """)
    assert findings == []


def test_order_cycle_flagged(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def one(self):
            with self._alock:
                with self._block:
                    self.a()

        def two(self):
            with self._block:
                with self._alock:
                    self.b()
    """)
    flagged = [f for f in findings if f.rule == "CRDT211"]
    assert flagged and any("cycle" in f.message for f in flagged)


# ------------------------------------------------------------- CRDT212

def test_dropped_claim_flagged(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def fire(self, lane):
            lane.claim()
    """)
    assert "CRDT212" in _rules(findings)
    assert "discarded" in findings[0].message


def test_ticket_normal_path_drop_flagged(tmp_path):
    findings = _flow_snippet(tmp_path, """
        def admit(self, q):
            t = q.submit_many([1])
            if self.closed:
                return None
            return t.wait(1.0)
    """)
    assert "CRDT212" in _rules(findings)


def test_ticket_exception_paths_are_exempt(tmp_path):
    """A Ticket abandoned by an exception sheds cooperatively (its lane
    flushes on deadline) — only normal-path drops flag."""
    findings = _flow_snippet(tmp_path, """
        def admit(self, q):
            t = q.submit_many([1])
            self.account()
            return t.wait(5.0)
    """)
    assert findings == []


def test_ticket_comprehension_is_clean(tmp_path):
    """Tickets hold no lock: building them in a comprehension (what
    ``_submit_groups`` does under the door lock) is fine."""
    findings = _flow_snippet(tmp_path, """
        def submit_groups(self, groups):
            with self._adm_lock:
                return [q.submit_many(items) for q, items in groups]
    """)
    assert findings == []


def test_escape_transfers_obligation(tmp_path):
    """Handles returned/stored/passed to a callee are the new owner's
    problem — the land_all_inline-style helper over a pendings param is
    clean, and so is handing a bound claim off."""
    findings = _flow_snippet(tmp_path, """
        def land_all_inline(pendings):
            total = 0
            for p in pendings:
                total += p.commit_inline()
            return total

        def handoff(self, lane):
            claim = lane.claim()
            self.landings.append(claim)
            return self.drain_later()
    """)
    assert findings == []


# ------------------------------------------------------------- CRDT213

def test_host_sync_under_node_lock_flagged(tmp_path):
    findings = _flow_snippet(tmp_path, """
        import numpy as np

        def snapshot(self):
            with self._lock:
                return np.asarray(self.rows)
    """)
    assert _rules(findings) == ["CRDT213"]
    assert findings[0].severity == "warn"


def test_transitive_blocking_under_lock_flagged(tmp_path):
    findings = _flow_snippet(tmp_path, """
        import time

        class Lane:
            def settle(self):
                time.sleep(0.1)

            def drain(self, other):
                other._drain_lock.acquire()
                try:
                    self.settle()
                finally:
                    other._drain_lock.release()
    """)
    assert _rules(findings) == ["CRDT213"]


def test_blocking_outside_sensitive_locks_is_clean(tmp_path):
    findings = _flow_snippet(tmp_path, """
        import numpy as np
        import time

        def poll(self):
            time.sleep(0.1)
            return np.asarray(self.rows)

        def account(self):
            with self._gauge_lock:
                self.n += 1
    """)
    assert findings == []


# ------------------------------------------------------- rules & bridge

def test_flow_rules_are_listed():
    for rule in ("CRDT210", "CRDT211", "CRDT212", "CRDT213"):
        assert rule in analysis.RULES
    assert analysis.SEVERITY["CRDT210"] == "error"
    assert analysis.SEVERITY["CRDT211"] == "error"
    assert analysis.SEVERITY["CRDT212"] == "error"
    assert analysis.SEVERITY["CRDT213"] == "warn"


def test_bridge_maps_witness_to_covering_finding():
    finding = Finding(rule="CRDT210", path="crdt_tpu/ingest/admission.py",
                      line=249, scope="AdmissionQueue.claim",
                      message="m", detail="self._drain_lock|raise")
    covered = flow.map_witnesses(
        ["race on AdmissionQueue._pending:\n"
         "  writer: crdt_tpu/ingest/admission.py:251 in claim\n"
         "  reader: crdt_tpu/ingest/admission.py:210 in submit_many"],
        findings=[finding])
    (m,) = covered
    assert m["covered"] and "CRDT210" in m["covered_by"][0]

    uncovered = flow.map_witnesses(
        ["race on Metrics._vals:\n"
         "  writer: crdt_tpu/utils/metrics.py:60 in inc"],
        findings=[finding])
    assert uncovered[0]["covered"] is False


def test_bridge_report_shape():
    rpt = flow.bridge_report([])
    assert rpt == {"witness_count": 0, "mapped": [], "uncovered_count": 0}


# ----------------------------------------------------------- tree smoke

def test_flow_layer_runs_over_package_without_errors():
    """The shipped tree is CRDT210/211/212-clean (errors are fixed, not
    baselined) — the flow half of the clean-tree invariant."""
    findings = flow.check_files(
        analysis.iter_py_files([analysis.package_root()]),
        analysis.repo_root())
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.render() for f in errors)
