"""Cross-daemon map lattice (round-5, VERDICT round-4 missing #3/task 5):
the PN-composition map served through NodeHost daemons, the coordinator-
scheduled reset barrier riding the network-barrier machinery, and the
stale-snapshot restore racing a reset barrier ACROSS PROCESS BOUNDARIES
(in-thread NodeHosts here — real subprocess daemons + SIGKILL in
harness/crashsoak.py's map schedule)."""
import threading

import pytest

from crdt_tpu.api.net import NodeHost, RemotePeer


@pytest.fixture
def trio():
    hosts = [NodeHost(rid=r, peers=[]) for r in range(3)]
    for h in hosts:
        h.agent.peers = [RemotePeer(o.url) for o in hosts if o is not h]
        threading.Thread(target=h._server.serve_forever, daemon=True).start()
    yield hosts
    for h in hosts:
        h._server.shutdown()
        h._server.server_close()


def _converge(hosts, rounds=6):
    for _ in range(rounds):
        for h in hosts:
            for peer in h.agent.peers:
                h.agent.map_pull(peer)


def test_map_http_surface_and_convergence(trio):
    a, b, c = trio
    pa = RemotePeer(a.url)
    # the wire surface end to end: upd/rem over HTTP, gossip pulls
    assert pa._post("/map/upd", {"key": "x", "delta": 5})
    assert pa._post("/map/upd", {"key": "x", "delta": -2})
    assert RemotePeer(b.url)._post("/map/upd", {"key": "y", "delta": 7})
    _converge(trio)
    import json
    import urllib.request

    for h in trio:
        with urllib.request.urlopen(h.url + "/map") as res:
            items = json.loads(res.read())["items"]
        assert items == {"x": 3, "y": 7}
    # vv endpoint serves (vv, epochs)
    vv, epochs = pa.map_vv()
    assert vv and epochs == {}


def test_map_reset_barrier_over_the_network(trio):
    a, b, c = trio
    a.map_node.upd("gone", 9)
    a.map_node.upd("kept", 4)
    _converge(trio)
    b.map_node.rem("gone")
    _converge(trio)
    # coordinator (a) schedules the barrier through the agent machinery
    epochs, status = a.agent.map_reset_once()
    assert epochs == {"gone": 1} and status == "reset"
    # the POST push landed everywhere (no gossip needed)
    for h in trio:
        assert h.map_node.epochs() == {"gone": 1}
        assert h.map_node.items() == {"kept": 4}
    # a member that misses the push (c rolled back) heals via gossip
    # (epoch rides the payload) — simulated by direct adopt of nothing
    assert a.agent.metrics.snapshot()["map_resets_scheduled"] == 1


def test_map_barrier_skipped_when_member_unreachable(trio):
    a, b, c = trio
    a.map_node.upd("k", 1)
    _converge(trio)
    b.map_node.rem("k")
    _converge(trio)
    c.map_node.set_alive(False)
    assert a.agent.map_reset_once() == ({}, "skipped")
    c.map_node.set_alive(True)
    assert a.agent.map_reset_once() == ({"k": 1}, "reset")


def test_stale_snapshot_restore_races_reset_barrier(tmp_path, trio):
    """The epoch absorption rule's hard case ACROSS the wire: a daemon
    checkpoints, the fleet agrees a reset AFTER the snapshot, the daemon
    is replaced by a restore from the stale snapshot (pre-barrier epoch,
    dominated records), writes on the stale state, then rejoins."""
    from crdt_tpu.utils import checkpoint as ckpt

    a, b, c = trio
    a.map_node.upd("k", 5)
    a.map_node.upd("stay", 2)
    _converge(trio)
    # c checkpoints BEFORE the remove + barrier
    snap_dir = str(tmp_path / "c")
    ckpt.save_node_atomic(snap_dir, c.node, set_node=c.set_node,
                          seq_node=c.seq_node, map_node=c.map_node)
    b.map_node.rem("k")
    _converge(trio)
    epochs, status = a.agent.map_reset_once()
    assert epochs == {"k": 1} and status == "reset"
    # c crashes; a fresh host restores the STALE snapshot (same rid —
    # the single-writer-window restore; incarnation-rid restores are the
    # crashsoak's department)
    c._server.shutdown()
    c._server.server_close()
    c2 = NodeHost(rid=2, peers=[a.url, b.url], checkpoint_dir=snap_dir)
    assert c2.restored
    threading.Thread(target=c2._server.serve_forever, daemon=True).start()
    try:
        # the stale state resurrected the reset key locally...
        assert c2.map_node.value("k") == 5
        assert c2.map_node.epochs() == {}
        # ...and even writes on it at the old epoch
        c2.map_node.upd("k", 100)
        # one pull absorbs the reset; the stale-epoch update is dominated
        for peer in c2.agent.peers:
            c2.agent.map_pull(peer)
        assert c2.map_node.epochs() == {"k": 1}
        assert c2.map_node.value("k") is None
        assert c2.map_node.value("stay") == 2
        # and the fleet stays converged when pulling FROM the stale node
        # (its payload carried old-epoch ops — void on arrival)
        for h in (a, b):
            h.agent.map_pull(RemotePeer(c2.url))
            assert h.map_node.value("k") is None
            assert h.map_node.value("stay") == 2
    finally:
        c2._server.shutdown()
        c2._server.server_close()


def test_admin_map_routes(trio):
    import json
    import urllib.request

    a = trio[0]
    a.map_node.upd("z", 3)
    req = urllib.request.Request(
        trio[1].url + "/admin/map_pull",
        data=json.dumps({"peer": a.url}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as res:
        assert json.loads(res.read())["pulled"] is True
    assert trio[1].map_node.value("z") == 3
    # admin barrier route (coordinator = a): nothing stably removed -> {}
    req = urllib.request.Request(
        a.url + "/admin/map_barrier", data=b"{}",
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as res:
        out = json.loads(res.read())
    assert out["epochs"] == {} and out["status"] == "noop"
