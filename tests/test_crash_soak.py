"""Crash-recovery soak (VERDICT round 1 #3): real SIGKILLs of daemon
subprocesses + checkpoint-restore into the live fleet while compaction
barriers run.  The scripted test forces the exact dangerous interleaving
the verdict called out — restore from a PRE-barrier snapshot (stale
compaction frontier) into a fleet whose barriers keep advancing — and the
random test lets the schedule find its own interleavings.
"""
from __future__ import annotations

import json

import pytest

from crdt_tpu.harness.crashsoak import CrashSoakRunner, _http


@pytest.fixture
def fleet():
    r = CrashSoakRunner(n=3, seed=7)
    yield r
    r.close()


def _write(runner, slot, cmd):
    d = runner.daemons[slot]
    code, _ = _http(d.url + "/data", "POST", cmd)
    assert code == 200
    rid = d.wire_rid
    seq = runner.accepted_per_boot.get(rid, 0)
    runner.accepted_per_boot[rid] = seq + 1
    runner.ops.append((rid, seq, dict(cmd)))
    runner.report.writes_accepted += 1


def _pull_all(runner):
    for d in runner.daemons:
        if not d.running:
            continue
        for peer in d.peer_urls:
            code, body = _http(d.url + "/admin/pull", "POST", {"peer": peer})
            assert code == 200, body


def _barrier(runner):
    code, body = _http(runner.daemons[0].url + "/admin/barrier", "POST", {})
    assert code == 200, body
    return json.loads(body)["frontier"]


def test_stale_frontier_restore_under_barriers(fleet):
    r = fleet
    # 1. writes everywhere, fully gossiped
    for slot in range(3):
        _write(r, slot, {"a": str(slot + 1)})
    _pull_all(r)
    # 2. node 2 checkpoints NOW — pre-barrier snapshot (frontier = empty)
    code, body = _http(r.daemons[2].url + "/admin/checkpoint", "POST", {})
    assert code == 200, body
    r.ckpt_watermark[r.daemons[2].wire_rid] = r.accepted_per_boot.get(
        r.daemons[2].wire_rid, 0)
    # 3. a barrier advances the WHOLE fleet's frontier past that snapshot
    frontier = _barrier(r)
    assert frontier, "fleet was fully converged; barrier must fold"
    # 4. more writes + gossip, then SIGKILL node 2 and restore it from the
    #    stale pre-barrier snapshot INTO the live fleet
    _write(r, 0, {"b": "10"})
    _pull_all(r)
    r.daemons[2].sigkill()
    r.daemons[2].spawn()  # restores pre-barrier snapshot, fresh incarnation
    # the restored daemon's frontier is a stale ancestor of the fleet's
    code, body = _http(r.daemons[2].url + "/vv")
    assert code == 200
    stale = json.loads(body)["frontier"]
    code, body = _http(r.daemons[0].url + "/vv")
    live = json.loads(body)["frontier"]
    assert stale != live and all(
        int(stale.get(k, -1)) <= int(v) for k, v in live.items()
    ), f"restored frontier {stale} must be a chain ancestor of {live}"
    # 5. barriers keep running while the stale node rejoins: the chain rule
    #    must hold (no 500s anywhere, which the helpers assert), then the
    #    restored node catches up by gossip frontier adoption
    _write(r, 1, {"c": "-4"})
    _barrier(r)  # may fold or skip; must never error
    _pull_all(r)
    _barrier(r)
    # 6. heal: full invariants I1-I4
    report = r.heal_and_check()
    assert report.rounds_to_converge >= 0
    # nothing was lost: node 2 was checkpointed before its kill
    assert report.ops_lost_to_crashes == 0
    # and its post-restore state includes everything, incl. pre-barrier ops
    want_a = 1 + 2 + 3
    state = json.loads(_http(r.daemons[2].url + "/data")[1])
    assert state["a"] == str(want_a)
    assert state["b"] == "10" and state["c"] == "-4"


def test_crash_loses_only_post_snapshot_suffix(fleet):
    """Un-checkpointed, un-gossiped writes die with the process (gossip-as-
    checkpoint, SURVEY.md §5); everything else survives — and the vv-prefix
    accounting in heal_and_check proves exactly that."""
    r = fleet
    _write(r, 1, {"x": "5"})
    _pull_all(r)  # x gossiped: survives the kill without any checkpoint
    code, _ = _http(r.daemons[1].url + "/admin/checkpoint", "POST", {})
    assert code == 200
    r.ckpt_watermark[r.daemons[1].wire_rid] = r.accepted_per_boot.get(
        r.daemons[1].wire_rid, 0)
    _write(r, 1, {"y": "7"})   # post-snapshot, never gossiped: will be lost
    r.daemons[1].sigkill()
    r.daemons[1].spawn()
    report = r.heal_and_check()
    assert report.ops_lost_to_crashes == 1  # exactly the y write
    state = json.loads(_http(r.daemons[0].url + "/data")[1])
    assert state.get("x") == "5" and "y" not in state


def test_random_crash_schedule(request):
    steps = 300 if request.config.getoption("--long") else 60
    # seed 3 under the round-5 step distribution (map workload added):
    # 2 SIGKILLs/restores, 3 checkpoints, 6 KV + 3 set + 4 seq + 2 map
    # ops in 60 steps (probed)
    runner = CrashSoakRunner(n=3, seed=3)
    report = runner.run(steps)
    # the schedule must actually exercise the crash machinery
    assert report.sigkills >= 1 and report.restores >= 1, report
    assert report.checkpoints >= 1, report
    assert report.writes_accepted > 0
    assert report.rounds_to_converge >= 0
    # the set AND seq workloads must be exercised by the same schedule
    assert report.set_adds >= 1, report
    assert report.seq_inserts >= 1, report
    assert report.map_upds >= 1, report


def _set_add(runner, slot, elem):
    d = runner.daemons[slot]
    code, body = _http(d.url + "/set/add", "POST", {"elem": elem})
    assert code == 200, body
    got = json.loads(body)
    rid = d.wire_rid
    seq = runner.set_accepted_per_boot.get(rid, 0)
    assert (got["rid"], got["seq"]) == (rid, seq)
    runner.set_accepted_per_boot[rid] = seq + 1
    runner.set_adds.append((rid, seq, elem))


def _set_remove(runner, slot, elem):
    d = runner.daemons[slot]
    code, body = _http(d.url + "/set/remove", "POST", {"elem": elem})
    assert code == 200, body
    got = json.loads(body)
    assert got["removed"], f"observed-remove found no live tag for {elem}"
    rid = d.wire_rid
    seq = runner.set_accepted_per_boot.get(rid, 0)
    runner.set_accepted_per_boot[rid] = seq + 1
    runner.set_removes.append(
        (rid, seq, [tuple(map(int, t)) for t in got["tags"]])
    )


def _set_pull_all(runner):
    for d in runner.daemons:
        if not d.running:
            continue
        for peer in d.peer_urls:
            code, body = _http(d.url + "/admin/set_pull", "POST",
                               {"peer": peer})
            assert code == 200, body


def _set_barrier(runner):
    code, body = _http(runner.daemons[0].url + "/admin/set_barrier",
                       "POST", {})
    assert code == 200, body
    return json.loads(body)["floor"]


def test_stale_floor_restore_under_gc_barriers(fleet):
    """The round-3 scripted interleaving: a node restored from a PRE-GC-
    barrier snapshot (stale floor, collected rows still live in it) rejoins
    a fleet whose GC barriers keep advancing — no resurrection, no lost
    removal, floors stay chained (S1-S3)."""
    r = fleet
    for slot in range(3):
        _set_add(r, slot, f"e{slot}")
    _set_pull_all(r)
    _set_pull_all(r)  # full mesh: everyone holds all three adds
    # node 2 checkpoints NOW: pre-barrier snapshot (floor = empty, and it
    # still holds e0 LIVE with no knowledge of the upcoming removal)
    code, body = _http(r.daemons[2].url + "/admin/checkpoint", "POST", {})
    assert code == 200, body
    r.set_ckpt_watermark[r.daemons[2].wire_rid] = (
        r.set_accepted_per_boot.get(r.daemons[2].wire_rid, 0)
    )
    # remove e0 and run a GC barrier that COLLECTS it fleet-wide
    _set_remove(r, 0, "e0")
    _set_pull_all(r)
    _set_pull_all(r)
    floor = _set_barrier(r)
    assert floor, "converged fleet: the GC barrier must fold"
    r.last_set_floor = {int(k): int(v) for k, v in floor.items()}
    # SIGKILL node 2, restore from the stale snapshot into the live fleet:
    # its restored table holds e0 live under a floor the fleet has passed
    r.daemons[2].sigkill()
    r.daemons[2].spawn()
    code, body = _http(r.daemons[2].url + "/set")
    assert code == 200
    assert "e0" in json.loads(body)["members"], (
        "restored pre-barrier snapshot must still hold the collected tag"
    )
    # barriers keep running while the stale node rejoins (skip or fold,
    # never 500), then the full-payload suppression kills the zombie tag
    _set_barrier(r)
    _set_pull_all(r)
    _set_barrier(r)
    report = r.heal_and_check()
    assert report.set_ops_lost == 0  # everything was checkpointed/gossiped
    members = json.loads(_http(r.daemons[2].url + "/set")[1])["members"]
    assert "e0" not in members, "collected tag resurrected (S1c)"
    assert set(members) == {"e1", "e2"}
