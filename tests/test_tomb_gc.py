"""Tombstone GC (crdt_tpu.models.tomb_gc): transparency, capacity
reclamation, resurrection prevention, late-tombstone preservation, and the
floor chain rule — for both OR-Set and RSeq adapters."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.models import orset, rseq, tomb_gc
from crdt_tpu.parallel import swarm

W = 4       # writers == replicas
CAP = 64
AD = orset.GC_ADAPTER


def _add(g, elem, rid, seq):
    return g.replace(inner=orset.add(g.inner, elem, rid, seq))


def _remove(g, elem):
    return g.replace(inner=orset.remove(g.inner, elem))


def _members(g):
    return set(np.nonzero(np.asarray(orset.member_mask(g.inner, 100)))[0])


def _stack(states):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _unstack(sw_state, r):
    return [jax.tree.map(lambda x: x[i], sw_state) for i in range(r)]


def _join(a, b):
    return tomb_gc.join(a, b, AD)


def test_received_vv_and_floor_clamp():
    g = tomb_gc.wrap(orset.empty(CAP), W)
    g = _add(g, 5, 1, 0)
    g = _add(g, 6, 1, 1)
    g = _add(g, 7, 3, 0)
    vv = np.asarray(tomb_gc.received_vv(g, AD))
    assert vv.tolist() == [-1, 1, -1, 0]
    # collect clamps to received: a floor beyond knowledge must not stick
    g2 = tomb_gc.collect(g, jnp.asarray([5, 5, 5, 5], jnp.int32), AD)
    assert np.asarray(g2.floor).tolist() == [-1, 1, -1, 0]


def test_gc_reclaims_capacity_and_is_transparent():
    g = tomb_gc.wrap(orset.empty(CAP), W)
    for i in range(20):
        g = _add(g, i, 0, i)
    for i in range(15):
        g = _remove(g, i)
    states = [g for _ in range(W)]  # fully converged swarm
    sw = swarm.make(_stack(states))
    before = _members(states[0])
    sw2 = tomb_gc.gc_round(sw, AD, orset.empty(CAP))
    after = _unstack(sw2.state, W)
    for rep in after:
        assert _members(rep) == before, "GC must not change the member set"
        assert int(orset.size(rep.inner)) == 5, "tombstoned rows reclaimed"
        assert np.asarray(rep.floor).tolist() == [19, -1, -1, -1]


def test_no_resurrection_from_stale_replica():
    """C holds a tag live, misses the remove AND the GC barrier; its rejoin
    must not resurrect the element."""
    c = tomb_gc.wrap(orset.empty(CAP), W)
    c = _add(c, 5, 2, 0)
    a = b = c  # gossiped to everyone
    a = _remove(a, 5)
    b = _join(b, a)  # B learns the tombstone; C does not (dead)
    sw = swarm.make(_stack([a, b, c]), jnp.asarray([True, True, False]))
    sw = tomb_gc.gc_round(sw, AD, orset.empty(CAP))
    a2, b2, c2 = _unstack(sw.state, 3)
    assert int(orset.size(a2.inner)) == 0  # collected
    assert np.asarray(c2.floor).tolist() == [-1] * W  # C untouched
    rejoined = _join(c2, a2)
    assert _members(rejoined) == set()
    assert int(orset.size(rejoined.inner)) == 0
    # and the other direction (A pulls from stale C) agrees
    assert _members(_join(a2, c2)) == set()


def test_late_tombstone_still_applies():
    """C removed the tag locally but never gossiped it out, then missed the
    barrier; the element is live (and floor-covered) everywhere else.  C's
    rejoin must apply the removal, not lose it."""
    c = tomb_gc.wrap(orset.empty(CAP), W)
    c = _add(c, 5, 2, 0)
    a = b = c
    c = _remove(c, 5)  # only C knows
    sw = swarm.make(_stack([a, b, c]), jnp.asarray([True, True, False]))
    sw = tomb_gc.gc_round(sw, AD, orset.empty(CAP))
    a2, b2, c2 = _unstack(sw.state, 3)
    assert _members(a2) == {5}  # live rows are never collected
    assert np.asarray(a2.floor).tolist() == [-1, -1, 0, -1]
    rejoined = _join(a2, c2)
    assert _members(rejoined) == set(), "late tombstone must OR in"
    # a later barrier collects the now-tombstoned row
    sw3 = swarm.make(_stack([rejoined, rejoined, rejoined]))
    sw3 = tomb_gc.gc_round(sw3, AD, orset.empty(CAP))
    assert int(orset.size(_unstack(sw3.state, 3)[0].inner)) == 0


def test_floor_chain_and_advance_with_dead_replica():
    """Barriers keep advancing while a replica is dead (its stale floor is
    dominated), and the revived replica catches up through one join."""
    g = tomb_gc.wrap(orset.empty(CAP), W)
    g = _add(g, 1, 0, 0)
    g = _remove(g, 1)
    sw = swarm.make(_stack([g, g, g]))
    sw = tomb_gc.gc_round(sw, AD, orset.empty(CAP))  # barrier 1: all alive
    states = _unstack(sw.state, 3)
    # replica 2 dies; 0 and 1 keep writing and hold barrier 2
    a, b = states[0], states[1]
    a = _add(a, 2, 0, 1)
    a = _remove(a, 2)
    b = _join(b, a)
    sw2 = swarm.make(_stack([a, b, states[2]]),
                     jnp.asarray([True, True, False]))
    sw2 = tomb_gc.gc_round(sw2, AD, orset.empty(CAP))
    a2, b2, c2 = _unstack(sw2.state, 3)
    assert np.asarray(a2.floor).tolist() == [1, -1, -1, -1]
    assert np.asarray(c2.floor).tolist() == [0, -1, -1, -1]  # stale chain
    rejoined = _join(c2, a2)
    assert np.asarray(rejoined.floor).tolist() == [1, -1, -1, -1]
    assert _members(rejoined) == set()


def test_gc_join_laws_on_simulated_history():
    """Commutativity/associativity/idempotence of the GC-aware join over
    states produced by a realistic history (adds, removes, gossip,
    barriers) — floors stay chain-comparable, which is the precondition."""
    rng = np.random.default_rng(7)
    states = [tomb_gc.wrap(orset.empty(CAP), W) for _ in range(W)]
    seqs = [0] * W
    for step in range(40):
        r = int(rng.integers(0, W))
        if rng.random() < 0.6:
            states[r] = _add(states[r], int(rng.integers(0, 30)), r, seqs[r])
            seqs[r] += 1
        else:
            m = _members(states[r])
            if m:
                states[r] = _remove(states[r], int(rng.choice(sorted(m))))
        if rng.random() < 0.3:
            i, j = rng.choice(W, 2, replace=False)
            states[int(i)] = _join(states[int(i)], states[int(j)])
        if step % 13 == 12:
            sw = tomb_gc.gc_round(swarm.make(_stack(states)), AD,
                                  orset.empty(CAP))
            states = _unstack(sw.state, W)

    from tests.helpers import tree_equal

    a, b, c = states[0], states[1], states[2]
    assert tree_equal(_join(a, b), _join(b, a))
    assert tree_equal(_join(_join(a, b), c), _join(a, _join(b, c)))
    assert tree_equal(_join(a, a), a)


def test_gc_and_columnar_states_checkpoint_roundtrip(tmp_path):
    """The generic swarm snapshot path must cover the round-2 lattices:
    GC-wrapped OR-Sets (floor plane included) and the columnar OpLog
    (static bits restored from the template)."""
    from crdt_tpu.models import oplog, oplog_columnar as oc
    from crdt_tpu.utils import checkpoint
    from tests.helpers import tree_equal

    g = tomb_gc.wrap(orset.empty(16), W)
    g = _add(g, 5, 1, 0)
    g = tomb_gc.collect(g, jnp.asarray([-1, 0, -1, -1], jnp.int32), AD)
    checkpoint.save_swarm(str(tmp_path / "gc"), g)
    back = checkpoint.restore_swarm(
        str(tmp_path / "gc"), tomb_gc.wrap(orset.empty(16), W)
    )
    assert tree_equal(back, g)

    logs = [oplog.empty(8) for _ in range(2)]
    col = oc.stack(jax.tree.map(lambda *xs: jnp.stack(xs), *logs),
                   bits=(4, 22, 5))
    checkpoint.save_swarm(str(tmp_path / "col"), col)
    back = checkpoint.restore_swarm(str(tmp_path / "col"), col)
    assert back.bits == (4, 22, 5)
    assert tree_equal(back, col)


def test_gc_barrier_refuses_on_overflow():
    """A barrier whose converge-union would truncate must raise GcOverflow
    instead of advancing the floor over silently-dropped rows; growing the
    fleet first (orset.grow) is the recovery path."""
    small = 8
    a = tomb_gc.wrap(orset.empty(small), W)
    b = tomb_gc.wrap(orset.empty(small), W)
    for i in range(6):
        a = _add(a, i, 0, i)          # disjoint tag sets: union = 12 > 8
        b = b.replace(inner=orset.add(b.inner, 10 + i, 1, i))
    sw = swarm.make(_stack([a, b]))
    with pytest.raises(tomb_gc.GcOverflow, match="12 rows"):
        tomb_gc.gc_round(sw, AD, orset.empty(small))
    grown = [g.replace(inner=orset.grow(g.inner, 16)) for g in (a, b)]
    sw2 = tomb_gc.gc_round(swarm.make(_stack(grown)), AD, orset.empty(16))
    g2 = _unstack(sw2.state, 2)[0]
    assert int(orset.size(g2.inner)) == 12  # all live, nothing collected


def test_join_checked_rejects_mismatched_shapes():
    """Advisor round 2: mixed capacities/layouts must raise loudly (the
    bare sorted_union assert vanishes under python -O, and the capacity
    slice would otherwise make the join silently asymmetric)."""
    a = tomb_gc.wrap(orset.empty(16), W)
    b = tomb_gc.wrap(orset.empty(32), W)
    with pytest.raises(ValueError, match="equal capacities|key layouts"):
        tomb_gc.join_checked(a, b, AD)
    c = tomb_gc.wrap(orset.empty(16), W + 1)
    with pytest.raises(ValueError, match="writer counts"):
        tomb_gc.join_checked(a, c, AD)
    # mixed-depth RSeq states carry different key-column counts
    ra = tomb_gc.wrap(rseq.empty(16), W)
    rb = tomb_gc.wrap(rseq.widen(rseq.empty(16), rseq.DEPTH + 1), W)
    with pytest.raises(ValueError, match="key layouts"):
        tomb_gc.join_checked(ra, rb, rseq.GC_ADAPTER)


def test_join_refuses_overflow():
    """Advisor round 2: the public convenience ``join`` must raise on
    capacity overflow instead of silently truncating (truncation breaks
    per-writer seq contiguity — permanent data loss under GC)."""
    a = tomb_gc.wrap(orset.empty(8), W)
    b = tomb_gc.wrap(orset.empty(8), W)
    for i in range(6):
        a = _add(a, i, 0, i)
        b = _add(b, 10 + i, 1, i)
    with pytest.raises(tomb_gc.GcOverflow, match="12 rows"):
        tomb_gc.join(a, b, AD)


def test_next_seq_is_floor_aware():
    """After GC collects a writer's rows, the table max understates the used
    seq range; next_seq must resume above the floor instead."""
    g = tomb_gc.wrap(orset.empty(CAP), W)
    for i in range(5):
        g = _add(g, i, 1, i)
    for i in range(5):
        g = _remove(g, i)
    sw = tomb_gc.gc_round(swarm.make(_stack([g, g])), AD, orset.empty(CAP))
    g2 = _unstack(sw.state, 2)[0]
    assert int(orset.size(g2.inner)) == 0  # all collected: table is empty
    assert tomb_gc.next_seq(g2, AD, 1) == 5
    assert tomb_gc.next_seq(g2, AD, 0) == 0


# ---- RSeq adapter ----------------------------------------------------------


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_rseq_gc_reclaims_and_preserves_order():
    w = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for i in range(20):
        w.append(i)
    for _ in range(10):
        w.delete_at(3)  # delete 3..12
    g = tomb_gc.wrap(w.state, W)
    before = rseq.to_list(g.inner)
    assert int(rseq.n_rows(g.inner)) == 20
    sw = tomb_gc.gc_round(swarm.make(_stack([g, g, g])), rseq.GC_ADAPTER,
                          rseq.empty(CAP))
    g2 = _unstack(sw.state, 3)[0]
    assert rseq.to_list(g2.inner) == before
    assert int(rseq.n_rows(g2.inner)) == 10, "tombstones reclaimed"
    # editing continues on the collected table (anchors embed coordinate
    # copies, so surviving rows still order correctly)
    w2 = rseq.SeqWriter(g2.inner, rid=1)
    w2.insert_at(5, 99)
    assert rseq.to_list(w2.state)[5] == 99
    # a stale pre-GC state cannot resurrect the deleted run
    stale = tomb_gc.wrap(w.state, W)  # still has the tombstoned rows
    rejoined = tomb_gc.join(g2.replace(inner=w2.state), stale,
                            rseq.GC_ADAPTER)
    assert rseq.to_list(rejoined.inner) == rseq.to_list(w2.state)


def test_rseq_gc_no_resurrection_from_dead_writer():
    base = rseq.SeqWriter(rseq.empty(CAP), rid=0)
    for i in range(5):
        base.append(i)
    shared = tomb_gc.wrap(base.state, W)
    # replica 2 (dead soon) holds the full list; 0 deletes an element
    wa = rseq.SeqWriter(shared.inner, rid=1)
    wa.delete_at(2)
    a = shared.replace(inner=wa.state)
    sw = swarm.make(_stack([a, a, shared]), jnp.asarray([True, True, False]))
    sw = tomb_gc.gc_round(sw, rseq.GC_ADAPTER, rseq.empty(CAP))
    a2, _, c2 = _unstack(sw.state, 3)
    assert rseq.to_list(a2.inner) == [0, 1, 3, 4]
    assert int(rseq.n_rows(a2.inner)) == 4
    rejoined = tomb_gc.join(c2, a2, rseq.GC_ADAPTER)
    assert rseq.to_list(rejoined.inner) == [0, 1, 3, 4]
