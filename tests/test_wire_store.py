"""Native wire-store tests: the C++ gossip-payload emitter must produce
byte-for-byte-parseable JSON identical in content to the Python payload
path, across full dumps, deltas, pruning, and adversarial strings."""
import json

import pytest

from crdt_tpu import native
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.utils.clock import ManualClock

pytestmark = pytest.mark.skipif(
    not native.AVAILABLE, reason="native runtime unavailable"
)


def _node(rid=0):
    return ReplicaNode(rid=rid, clock=ManualClock(start=1000))


def test_full_dump_matches_python():
    n = _node()
    n.add_command({"x": "5", "y": "hello"})
    n.add_command({"x": "-3"})
    got = json.loads(n.gossip_payload_json())
    want = n.gossip_payload()
    assert got == want and len(got) == 2


def test_delta_matches_python():
    a, b = _node(0), _node(1)
    a.add_command({"x": "1"})
    a.add_command({"y": "2"})
    b.receive(a.gossip_payload())
    b.add_command({"z": "3"})
    since = b.version_vector()
    got = json.loads(a.gossip_payload_json(since=since))
    want = a.gossip_payload(since=since)
    assert got == want == {}
    since2 = {0: 0}  # missing a's second op
    got2 = json.loads(a.gossip_payload_json(since=since2))
    assert got2 == a.gossip_payload(since=since2)
    assert len(got2) == 1


def test_adversarial_strings():
    n = _node()
    nasty = {
        'k"quote': 'v\\backslash',
        "k\nnewline": "v\ttab",
        "k\x01ctrl": "v\x1f",
        "kλ∀-unicode": "v—em🎉",
    }
    for k, v in nasty.items():
        n.add_command({k: v})
    got = json.loads(n.gossip_payload_json())
    want = n.gossip_payload()
    assert got == want
    cmds = [list(c.items())[0] for c in got.values()]
    assert sorted(cmds) == sorted(nasty.items())


def test_receive_roundtrip_via_json():
    a, b = _node(0), _node(1)
    a.add_command({"x": "5", "s": 'he said "hi"'})
    b.receive(json.loads(a.gossip_payload_json()))
    assert b.get_state() == a.get_state()


def test_prune_mirrors_wire_store():
    n = _node()
    for i in range(5):
        n.add_command({f"k{i}": str(i)})
    assert len(n._wire) == 5
    n.compact({0: 2})  # folds seqs 0..2
    assert len(n._wire) == len(n._commands) == 2
    got = json.loads(n.gossip_payload_json(since=n.version_vector()))
    assert got == n.gossip_payload(since=n.version_vector())


def test_compaction_sections_fall_back_to_python():
    n = _node()
    for i in range(4):
        n.add_command({"a": "1"})
    n.compact({0: 3})
    body = json.loads(n.gossip_payload_json(since={}))  # fresh requester
    assert "__frontier__" in body and "__summary__" in body
    assert body == n.gossip_payload(since={})


def test_foreign_ops_always_shipped():
    n = _node()
    n.receive({"123456:-1:0": {"go": "7"}})  # Go-format peer op
    n.add_command({"x": "1"})
    since = {0: 0}  # covers our own op; foreign has no watermark
    got = json.loads(n.gossip_payload_json(since=since))
    assert got == n.gossip_payload(since=since)
    assert len(got) == 1 and list(got.values())[0] == {"go": "7"}


def test_dead_node_returns_none():
    n = _node()
    n.set_alive(False)
    assert n.gossip_payload_json() is None


def test_restore_rebuilds_wire(tmp_path):
    from crdt_tpu.utils import checkpoint

    n = _node()
    n.add_command({"x": "5"})
    path = str(tmp_path / "snap")
    checkpoint.save_node(path, n)
    m = _node()
    checkpoint.restore_node(path, m)
    assert json.loads(m.gossip_payload_json()) == m.gossip_payload()
    assert len(m._wire) == len(m._commands) == 1
