"""Shared test helpers: random lattice states and pytree equality."""
from __future__ import annotations

import jax
import numpy as np

from crdt_tpu.models import gcounter, lww, oplog, orset, pncounter


def tree_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def rand_gcounter(rng: np.random.Generator, n_nodes=8, batch=()):
    return gcounter.GCounter(
        counts=np.asarray(rng.integers(0, 100, (*batch, n_nodes)), np.int32)
    )


def rand_pncounter(rng: np.random.Generator, n_nodes=8, batch=()):
    return pncounter.PNCounter(
        pos=np.asarray(rng.integers(0, 100, (*batch, n_nodes)), np.int32),
        neg=np.asarray(rng.integers(0, 100, (*batch, n_nodes)), np.int32),
    )


def rand_lww(rng: np.random.Generator, batch=()):
    return lww.LWWRegister(
        ts=np.asarray(rng.integers(-1, 50, batch), np.int32),
        rid=np.asarray(rng.integers(0, 8, batch), np.int32),
        payload=np.asarray(rng.integers(0, 1000, batch), np.int32),
    )


def rand_orset(rng: np.random.Generator, capacity=32, n_elems=6, n_rids=3, fill=10):
    """Random OR-Set with `fill` unique tags (≤ capacity/3 so pairwise and
    three-way joins stay within capacity for law tests)."""
    s = orset.empty(capacity)
    taken = set()
    for _ in range(fill):
        while True:
            tag = (
                int(rng.integers(0, n_elems)),
                int(rng.integers(0, n_rids)),
                int(rng.integers(0, 50)),
            )
            if tag not in taken:
                taken.add(tag)
                break
        s = orset.add(s, *tag)
        if rng.random() < 0.3:
            s = orset.remove(s, tag[0])
    return s


def rand_ops(rng: np.random.Generator, n, n_keys=6, n_rids=3, numeric_frac=0.8):
    """Random op columns with unique (ts, rid, seq, key) rows."""
    rows = set()
    while len(rows) < n:
        rows.add(
            (
                int(rng.integers(0, 40)),
                int(rng.integers(0, n_rids)),
                int(rng.integers(0, 20)),
                int(rng.integers(0, n_keys)),
            )
        )
    rows = sorted(rows)
    is_num = rng.random(n) < numeric_frac
    val = np.where(
        is_num,
        rng.integers(-20, 21, n),
        rng.integers(0, 50, n),
    )
    return {
        "ts": np.asarray([r[0] for r in rows], np.int32),
        "rid": np.asarray([r[1] for r in rows], np.int32),
        "seq": np.asarray([r[2] for r in rows], np.int32),
        "key": np.asarray([r[3] for r in rows], np.int32),
        "val": np.asarray(val, np.int32),
        "payload": np.asarray(rng.integers(0, 100, n), np.int32),
        "is_num": np.asarray(is_num, bool),
    }


def rand_oplog(rng: np.random.Generator, capacity=64, n=12, **kw):
    return oplog.from_ops(capacity, rand_ops(rng, n, **kw))


def rand_oplog_family(rng: np.random.Generator, n_logs=3, capacity=64, pool=20, take=12, **kw):
    """Logs sampling from one shared op pool: identical (ts,rid,seq,key) rows
    carry identical payloads, as real replicated ops do."""
    ops = rand_ops(rng, pool, **kw)
    logs = []
    for _ in range(n_logs):
        idx = np.sort(rng.choice(pool, size=take, replace=False))
        logs.append(oplog.from_ops(capacity, {k: v[idx] for k, v in ops.items()}))
    return logs
