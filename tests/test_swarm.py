"""Swarm anti-entropy tests: random-peer gossip convergence, fault
injection, and one-shot convergence — the automated version of the
reference's eyeball-a-soak-run validation (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.models import gcounter, oplog, pncounter
from crdt_tpu.ops import joins
from crdt_tpu.parallel import swarm
from tests import helpers
from tests.helpers import tree_equal


def _counter_swarm(rng, r=16, n_nodes=8):
    # replica i starts knowing only its own increments (diagonal writes)
    counts = np.zeros((r, n_nodes), np.int32)
    for i in range(r):
        counts[i, i % n_nodes] = rng.integers(1, 50)
    return swarm.make(gcounter.GCounter(counts=jnp.asarray(counts)))


def test_gossip_rounds_converge_counter():
    rng = np.random.default_rng(0)
    s = _counter_swarm(rng)
    key = jax.random.key(0)
    join_b = gcounter.join  # elementwise ops broadcast over the replica axis
    neutral = gcounter.zero(8)
    for i in range(20):
        key, k = jax.random.split(key)
        peers = swarm.random_peers(k, swarm.n_replicas(s))
        s = swarm.gossip_round(s, peers, join_b)
        if int(swarm.n_diverged(s, join_b, neutral)) == 0:
            break
    assert int(swarm.n_diverged(s, join_b, neutral)) == 0
    # every replica's value equals the total of all writes
    vals = np.asarray(gcounter.value(s.state))
    assert (vals == vals[0]).all()


def test_one_shot_converge_equals_gossip_fixpoint():
    rng = np.random.default_rng(1)
    s = _counter_swarm(rng)
    neutral = gcounter.zero(8)
    s2 = swarm.converge(s, gcounter.join, neutral)
    assert int(swarm.n_diverged(s2, gcounter.join, neutral)) == 0
    total = np.asarray(s.state.counts).max(axis=0).sum()
    assert (np.asarray(gcounter.value(s2.state)) == total).all()


def test_dead_replica_excluded_then_catches_up():
    rng = np.random.default_rng(2)
    s = _counter_swarm(rng, r=8)
    neutral = gcounter.zero(8)
    dead = 3
    s = swarm.set_alive(s, dead, False)
    before = np.asarray(s.state.counts[dead]).copy()

    s2 = swarm.converge(s, gcounter.join, neutral)
    # dead replica's unique writes are invisible to the others...
    alive_val = np.asarray(gcounter.value(s2.state))[0]
    full_total = np.asarray(s.state.counts).max(axis=0).sum()
    assert alive_val == full_total - before.sum()
    # ...and its own state did not move
    assert (np.asarray(s2.state.counts[dead]) == before).all()

    # revive: one catch-up round restores full convergence (main.go:159 —
    # gossip always ships full state)
    s3 = swarm.set_alive(s2, dead, True)
    s3 = swarm.converge(s3, gcounter.join, neutral)
    assert (np.asarray(gcounter.value(s3.state)) == full_total).all()


def test_oplog_swarm_gossip_converges():
    rng = np.random.default_rng(3)
    r, cap = 8, 64
    logs = helpers.rand_oplog_family(rng, n_logs=r, capacity=cap, pool=30, take=10)
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *logs)
    s = swarm.make(state)
    join_b = jax.vmap(oplog.merge)
    neutral = oplog.empty(cap)

    key = jax.random.key(7)
    for _ in range(30):
        key, k = jax.random.split(key)
        peers = swarm.random_peers(k, r)
        s = swarm.gossip_round(s, peers, join_b)
        if int(swarm.n_diverged(s, join_b, neutral)) == 0:
            break
    assert int(swarm.n_diverged(s, join_b, neutral)) == 0

    # fixpoint state = union of all logs (order-free), same as one-shot
    one_shot = swarm.converge(swarm.make(state), join_b, neutral)
    assert tree_equal(s.state, one_shot.state)


def test_pncounter_swarm_value_conservation():
    rng = np.random.default_rng(4)
    r, nodes = 12, 12
    pos = np.zeros((r, nodes), np.int32)
    neg = np.zeros((r, nodes), np.int32)
    deltas = rng.integers(-20, -10, r)  # reference workload: all-negative
    for i, d in enumerate(deltas):
        neg[i, i] = -d
    s = swarm.make(pncounter.PNCounter(pos=jnp.asarray(pos), neg=jnp.asarray(neg)))
    s = swarm.converge(s, pncounter.join, pncounter.zero(nodes))
    assert (np.asarray(pncounter.value(s.state)) == deltas.sum()).all()
