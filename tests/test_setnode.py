"""SetNode (crdt_tpu.api.setnode): the OR-Set(+GC) across the process
boundary — wire format, floor-carrying delta transport, GC barriers, and
checkpoint round-trips.  The round-2 verdict's items 4 and 5: GC and delta
transport must COMPOSE (bounded payloads AND bounded tables), and the
suppression invariants must hold over the wire."""
import json

import numpy as np
import pytest

from crdt_tpu.api.setnode import (
    FLOOR_KEY,
    FULL_KEY,
    SetNode,
    set_barrier,
)


def _sync(a: SetNode, b: SetNode, rounds: int = 3) -> None:
    for _ in range(rounds):
        b.receive(a.gossip_payload(since=b.version_vector()))
        a.receive(b.gossip_payload(since=a.version_vector()))


def _barrier(nodes) -> dict:
    floor = set_barrier(nodes[0], [n.vv_snapshot() for n in nodes[1:]])
    for n in nodes:
        if floor:
            n.collect(floor)
    return floor


def test_add_remove_readd_converges():
    a, b = SetNode(rid=0), SetNode(rid=1)
    a.add("x")
    _sync(a, b)
    b.remove("x")
    a.add("x")  # concurrent re-add: fresh tag must survive (observed-remove)
    _sync(a, b)
    assert a.members() == b.members() == ["x"]
    a.remove("x")
    _sync(a, b)
    assert a.members() == b.members() == []


def test_delta_payloads_are_delta_sized():
    a, b = SetNode(rid=0), SetNode(rid=1)
    for i in range(10):
        a.add(f"e{i}")
    _sync(a, b)
    a.add("fresh")
    p = a.gossip_payload(since=b.version_vector())
    ops = [k for k in p if k not in (FLOOR_KEY, FULL_KEY)]
    assert ops == ["0:10"], f"delta must carry only the new op: {ops}"


def test_gc_composes_with_delta_transport():
    """The round-2 exclusion deleted: after a GC barrier both the tables
    AND the payloads stay bounded, and delta mode keeps working."""
    a, b = SetNode(rid=0), SetNode(rid=1)
    for i in range(20):
        a.add(f"e{i}")
    _sync(a, b)
    for i in range(15):
        a.remove(f"e{i}")
    _sync(a, b)
    floor = _barrier([a, b])
    assert floor, "barrier must fire on a converged pair"
    # tables reclaimed: 5 live adds remain (collected rows dropped)
    from crdt_tpu.models import orset

    assert int(orset.size(a.gc.inner)) == 5
    assert int(orset.size(b.gc.inner)) == 5
    # host records pruned: the 15 collected adds and the 15 removes whose
    # identities+targets the floor covers are gone
    assert len(a._ops) == 5
    # delta transport still works post-GC (vv dominates floor)
    a.add("post-gc")
    p = a.gossip_payload(since=b.version_vector())
    assert not p.get(FULL_KEY), "peer dominates the floor: delta mode"
    ops = [k for k in p if k not in (FLOOR_KEY, FULL_KEY)]
    assert len(ops) == 1
    b.receive(p)
    assert b.members() == a.members()


def test_full_fallback_for_stale_peer():
    """A peer whose vv is behind the sender's floor gets the full payload
    (marked), because collected ops cannot be re-shipped."""
    a, b = SetNode(rid=0), SetNode(rid=1)
    for i in range(6):
        a.add(f"e{i}")
    _sync(a, b)
    for i in range(4):
        a.remove(f"e{i}")
    _sync(a, b)
    _barrier([a, b])
    fresh = SetNode(rid=2)  # empty vv, behind the floor
    p = a.gossip_payload(since=fresh.version_vector())
    assert p.get(FULL_KEY) is True
    fresh.receive(p)
    assert fresh.members() == a.members()
    # and from here on, fresh gets deltas
    a.add("later")
    p2 = a.gossip_payload(since=fresh.version_vector())
    assert not p2.get(FULL_KEY)
    fresh.receive(p2)
    assert fresh.members() == a.members()


def test_no_resurrection_from_stale_live_copy():
    """C holds a tag live, misses the removal AND the barrier; the full
    payload's absence-implies-collected suppression must drop it."""
    a, b, c = SetNode(rid=0), SetNode(rid=1), SetNode(rid=2)
    a.add("x")
    _sync(a, b)
    _sync(a, c)  # everyone holds x live
    a.remove("x")
    _sync(a, b)  # c missed the removal
    floor = _barrier([a, b])  # c missed the barrier too
    assert floor
    assert a.members() == []
    # c pulls from a: its vv covers the add but its FLOOR is behind →
    # sender's floor isn't dominated... c's vv includes the add op (0:0)
    # and the remove op (0:1)? No — c missed the remove, vv[0] == 0 < 1.
    p = a.gossip_payload(since=c.version_vector())
    assert p.get(FULL_KEY) is True  # c's vv is behind a's floor
    c.receive(p)
    assert c.members() == []
    # and the reverse direction cannot resurrect either
    a.receive(c.gossip_payload(since=a.version_vector()))
    assert a.members() == []


def test_late_tombstone_still_applies():
    """C removed locally but never gossiped it out, then missed the
    barrier; C's remove op must still apply at the others (no lost
    removal)."""
    a, b, c = SetNode(rid=0), SetNode(rid=1), SetNode(rid=2)
    a.add("x")
    _sync(a, b)
    _sync(a, c)
    c.remove("x")  # only C knows
    floor = _barrier([a, b])  # barrier over a, b only; x is live there
    # x's add may be floor-covered at a/b, but it is LIVE — not collected
    a.receive(c.gossip_payload(since=a.version_vector()))
    assert a.members() == []
    _sync(a, b)
    assert b.members() == []


def test_remove_record_retained_until_targets_covered():
    """The remove-op prune rule: while the target add can still travel
    (floor doesn't cover it), every remove targeting it must be retained —
    an add arriving after its remover must land tombstoned."""
    a, b = SetNode(rid=0), SetNode(rid=1)
    a.add("x")       # op 0:0
    _sync(a, b)
    b.remove("x")    # op 1:0 targeting tag (0, 0)
    # deliver ONLY b's remove to a fresh node, then the add later
    c = SetNode(rid=2)
    pb = b.gossip_payload(since=c.version_vector())
    # hand-deliver just the remove op (simulates out-of-order arrival)
    remove_only = {
        k: v for k, v in pb.items()
        if k in (FLOOR_KEY, FULL_KEY) or "remove" in v
    }
    c.receive(remove_only)
    assert c.members() == []
    add_only = {
        k: v for k, v in pb.items()
        if k not in (FLOOR_KEY, FULL_KEY) and "add" in v
    }
    c.receive(add_only)
    assert c.members() == [], "add arriving after its remover must be dead"


def test_incomparable_floors_fail_loudly():
    a, b = SetNode(rid=0), SetNode(rid=1)
    a.add("x")
    b.add("y")
    _sync(a, b)
    a.remove("x")
    b.remove("y")
    _sync(a, b)
    # two "barriers" that each collected only one side's knowledge
    a.collect({0: 0})
    b.collect({1: 0})
    with pytest.raises(ValueError, match="incomparable"):
        a.receive(b.gossip_payload(since=a.version_vector()))


def test_snapshot_roundtrip_preserves_everything():
    a = SetNode(rid=0)
    b = SetNode(rid=1)
    for i in range(8):
        a.add(f"e{i}")
    _sync(a, b)
    for i in range(4):
        a.remove(f"e{i}")
    _sync(a, b)
    _barrier([a, b])
    a.add("post")
    b.receive(a.gossip_payload(since=b.version_vector()))
    b.remove("post")  # a hasn't seen this removal yet

    snap = json.loads(json.dumps(a.to_snapshot()))  # wire-safe JSON
    a2 = SetNode(rid=0)
    a2.from_snapshot(snap)
    assert a2.members() == a.members()
    assert a2.version_vector() == a.version_vector()
    assert a2._floor == a._floor
    assert a2._seq.count == a._seq.count
    # the restored node keeps converging (including b's pending removal)
    _sync(a2, b)
    assert a2.members() == b.members()


def test_snapshot_restore_under_fresh_incarnation_rid():
    """An incarnation restore (fresh rid) adopts the dead rid's ops as a
    frozen prefix and starts its own counter at 0."""
    a = SetNode(rid=0)
    a.add("x")
    a.add("y")
    snap = a.to_snapshot()
    a2 = SetNode(rid=64)  # fresh incarnation rid
    a2.from_snapshot(snap)
    assert a2.members() == ["x", "y"]
    assert a2._seq.count == 0
    ident = a2.add("z")
    assert ident == (64, 0), "fresh incarnation mints under its own rid"


def test_set_barrier_skips_on_unreachable_member():
    a = SetNode(rid=0)
    a.add("x")
    assert set_barrier(a, [None]) == {}


def test_tables_grow_on_overflow():
    a = SetNode(rid=0, capacity=4)
    for i in range(20):
        a.add(f"e{i}")
    assert len(a.members()) == 20
    assert a.gc.inner.capacity >= 20


def test_scheduled_set_gc_cadence_in_daemon_mode():
    """set_collect_every schedules GC barriers from the coordinator's live
    loop INDEPENDENTLY of compact_every (which may be 0 — mixed-fleet
    rule), so long-lived set fleets stay bounded without manual barriers."""
    import time

    from crdt_tpu.api.net import NodeHost, RemotePeer
    from crdt_tpu.utils.config import ClusterConfig

    cfg = ClusterConfig(gossip_period_ms=40, compact_every=0,
                        set_collect_every=2)
    h0 = NodeHost(rid=0, peers=[], port=0, config=cfg, coordinator=True)
    h1 = NodeHost(rid=1, peers=[], port=0, config=cfg)
    h0.start_server(); h1.start_server()
    h0.agent.peers = [RemotePeer(h1.url)]
    h1.agent.peers = [RemotePeer(h0.url)]
    try:
        for i in range(6):
            h0.set_node.add(f"e{i}")
        for i in range(4):
            h0.set_node.remove(f"e{i}")
        h0.agent.start(); h1.agent.start()
        deadline = time.time() + 8
        while time.time() < deadline:
            if h0.set_node.vv_snapshot()[1]:  # floor advanced
                break
            time.sleep(0.1)
        floor = h0.set_node.vv_snapshot()[1]
        assert floor, "scheduled set GC barrier never fired"
        from crdt_tpu.models import orset

        deadline = time.time() + 8
        while time.time() < deadline:
            if int(orset.size(h0.set_node.gc.inner)) == 2:
                break
            time.sleep(0.1)
        assert int(orset.size(h0.set_node.gc.inner)) == 2, "tombstones kept"
    finally:
        h0.agent.stop(); h1.agent.stop()
        h0.stop_server(); h1.stop_server()
