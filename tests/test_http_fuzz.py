"""HTTP-surface robustness: malformed requests and hostile peers must
produce error codes / skipped rounds — never kill a server thread, a pull
loop, or node state.  (The reference dies permanently on one malformed
gossip key, quirk §0.1.8, and 500s-then-continues on bad bodies,
§0.1.11.)"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from crdt_tpu.api.cluster import LocalCluster
from crdt_tpu.api.http_shim import HttpCluster
from crdt_tpu.api.net import NetworkAgent, RemotePeer
from crdt_tpu.utils.config import ClusterConfig


@pytest.fixture
def served():
    cluster = LocalCluster(ClusterConfig(n_replicas=2))
    http = HttpCluster(cluster)
    ports = http.start()
    yield cluster, [f"http://127.0.0.1:{p}" for p in ports]
    http.stop()


def _req(url, method="GET", data=None):
    # 30 s, not 5: a fuzz body that mints a real op (e.g. b"" on
    # /seq/insert -> append of "") pays the sequence lattice's first-touch
    # jit compile, which legitimately exceeds 5 s on a loaded CPU host
    # (same rationale as harness/crashsoak._http).  The invariant under
    # test is no-500/no-dead-thread, not latency.
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as res:
            return res.status, res.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.mark.parametrize("body", [
    b"not json at all",
    b"[1, 2, 3]",
    b'"just a string"',
    b"{",
    b"{\x00}",
])
def test_bad_post_data_bodies(served, body):
    cluster, urls = served
    code, text = _req(urls[0] + "/data", "POST", body)
    assert code == 500 and b"invalid" in text  # main.go:179-186
    # server healthy afterwards
    assert _req(urls[0] + "/ping")[0] == 200
    assert cluster.nodes[0].get_state() == {}


def test_bad_vv_query(served):
    _, urls = served
    assert _req(urls[0] + "/gossip?vv=garbage")[0] == 400
    assert _req(urls[0] + "/gossip?vv=%5B1%5D")[0] == 400
    assert _req(urls[0] + "/gossip")[0] == 200


def test_bad_compact_bodies(served):
    cluster, urls = served
    for body in (b"nope", b'{"frontier": "x"}', b'{"frontier": {"a": "b"}}'):
        assert _req(urls[0] + "/compact", "POST", body)[0] == 400
    assert cluster.nodes[0].frontier == {}


def test_unknown_paths_and_conditions(served):
    _, urls = served
    assert _req(urls[0] + "/nope")[0] == 404
    assert _req(urls[0] + "/data/extra")[0] == 404
    assert _req(urls[0] + "/condition/banana")[0] == 500  # main.go:146-149
    assert _req(urls[0] + "/condition")[0] == 500
    assert _req(urls[0] + "/ping")[0] == 200


def test_nested_json_values_coerced(served):
    cluster, urls = served
    code, _ = _req(urls[0] + "/data", "POST",
                   json.dumps({"k": {"nested": 1}}).encode())
    assert code == 200  # values are stringified, like Go's map[string]string-ish
    state = cluster.nodes[0].get_state()
    assert "k" in state


class _GarbageHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        body = b"\xff\xfe NOT JSON {{{"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_corrupt_peer_is_skipped_not_fatal():
    """A peer serving 200 + garbage bytes == unreachable: the pull round is
    skipped, the agent loop survives, and a later good peer still works."""
    from crdt_tpu.api.node import ReplicaNode

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _GarbageHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    bad_url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        node = ReplicaNode(rid=0)
        agent = NetworkAgent(node, [bad_url], ClusterConfig())
        assert agent.gossip_once() is False  # skip, no exception
        assert RemotePeer(bad_url).get_state() is None
        assert RemotePeer(bad_url).version_vector() is None
    finally:
        srv.shutdown()
        srv.server_close()


def test_malformed_wire_key_still_raises():
    """Inside VALID JSON, a malformed op key is a protocol violation and
    fails loudly (the fix for quirk §0.1.8's silent loop death)."""
    from crdt_tpu.api.node import ReplicaNode

    node = ReplicaNode(rid=0)
    with pytest.raises(ValueError):
        node.receive({"not-a-wire-key": {"x": "1"}})


@pytest.mark.parametrize("body", [b'"Service Unavailable"', b"null", b"[]", b"17"])
def test_valid_json_non_dict_peer_is_skipped(body):
    """A 200 with valid-JSON-but-not-an-object body (e.g. a proxy fronting
    a dead peer) must hit the same skip path as corrupt bytes."""
    from crdt_tpu.api.node import ReplicaNode

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        agent = NetworkAgent(ReplicaNode(rid=0), [url], ClusterConfig())
        assert agent.gossip_once() is False
        assert RemotePeer(url).gossip_payload() is None
    finally:
        srv.shutdown()
        srv.server_close()


# ---- extension-surface fuzz (/set/*, /seq/* — round 4) ----------------------


@pytest.mark.parametrize("path,bodies", [
    ("/set/add", [b"", b"[]", b"42", b"{bad", b'{"elem": {"a": 1}}']),
    ("/set/collect", [b"{bad", b'{"floor": "x"}', b'{"floor": {"a": "b"}}']),
    ("/seq/insert", [b"", b"[]", b"{bad", b'{"elem": "x", "index": "q"}']),
    ("/seq/remove", [b"{bad", b'{"index": null}', b'{"index": "x"}']),
    ("/seq/collect", [b"{bad", b'{"floor": {"a": "b"}}']),
])
def test_bad_extension_bodies_never_500(served, path, bodies):
    """Malformed bodies on the set/seq surfaces get 4xx/2xx — never a
    500 and never a dead server thread."""
    cluster, urls = served
    for body in bodies:
        code, _ = _req(urls[0] + path, method="POST", data=body)
        assert code in (200, 400, 502), (path, body, code)
    # the server is still healthy afterwards
    assert _req(urls[0] + "/ping")[0] == 200
    assert _req(urls[0] + "/set")[0] == 200
    assert _req(urls[0] + "/seq")[0] == 200


def test_bad_extension_vv_queries(served):
    cluster, urls = served
    for path in ("/set/gossip", "/seq/gossip"):
        code, _ = _req(urls[0] + path + "?vv=%7Bbad")
        assert code == 400
        code, _ = _req(urls[0] + path)
        assert code == 200


def test_seq_hostile_payloads_raise_loudly_and_mutate_nothing():
    """Malformed seq wire CONTENT (inside valid JSON) raises like
    ReplicaNode.receive — and the validation pass runs before any state
    mutates, so a bad row rejects its whole batch atomically."""
    from crdt_tpu.api.seqnode import SeqNode

    n = SeqNode(rid=0)
    n.append("keep")
    before_items = n.items()
    before_vv = n.version_vector()
    good = {"ins": "x", "path": [[1, 2, 1, 0]]}
    hostile = [
        {"1:0": {"ins": "a"}},                                # no path
        {"1:0": {"ins": "a", "path": []}},                    # empty path
        {"1:0": {"ins": "a", "path": [[1, 2, 9, 9]]}},        # identity forgery
        {"1:0": {"ins": "a", "path": [[1, 2]]}},              # wrong arity
        {"1:0": {"ins": "a", "path": [["x", 0, 1, 0]]}},      # non-numeric
        {"1:0": {"del": [1]}},                                # bad target
        {"1:0": {"nop": 1}},                                  # unknown kind
        {"garbage": good},                                    # bad wire key
        # a GOOD op batched with a bad one: the batch must reject whole
        {"1:0": dict(good), "1:1": {"ins": "b", "path": [[1, 3, 5, 5]]}},
    ]
    for payload in hostile:
        with pytest.raises((ValueError, KeyError, TypeError)):
            n.receive(payload)
        assert n.items() == before_items, payload
        assert n.version_vector() == before_vv, payload
    # and a clean payload still lands afterwards
    assert n.receive({"1:0": good}) == 1
    assert "x" in n.items()


def test_set_hostile_payloads_raise_loudly():
    from crdt_tpu.api.setnode import SetNode

    n = SetNode(rid=0)
    n.add("keep")
    before = n.members()
    for payload in (
        {"garbage": {"add": "a"}},
        {"1:x": {"add": "a"}},
    ):
        with pytest.raises(ValueError):
            n.receive(payload)
        assert n.members() == before
