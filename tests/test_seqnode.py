"""SeqNode: the sequence lattice across the process boundary (VERDICT
round 3, item 4) — op-identified inserts/removes with path-key wire
encoding, per-writer vv deltas, floor-carrying GC, crash-safe snapshot
sections, and the /seq/* HTTP surface."""
import json
import urllib.request

import pytest

from crdt_tpu.api.seqnode import FLOOR_KEY, FULL_KEY, SeqNode, seq_barrier


def pull(dst: SeqNode, src: SeqNode, delta: bool = True) -> int:
    """One pull round dst <- src (the NetworkAgent.seq_pull shape)."""
    since = dst.version_vector() if delta else None
    return dst.receive(src.gossip_payload(since=since))


def sync(a: SeqNode, b: SeqNode) -> None:
    for _ in range(2):
        pull(a, b)
        pull(b, a)


def test_basic_editing_and_order():
    n = SeqNode(rid=0)
    assert n.append("a") == (0, 0)
    assert n.append("c") == (0, 1)
    assert n.insert_at(1, "b") == (0, 2)
    assert n.items() == ["a", "b", "c"]
    assert n.remove_at(1) == (0, 3)
    assert n.items() == ["a", "c"]
    # out-of-range remove mints nothing
    assert n.remove_at(5) is None
    assert n.version_vector() == {0: 3}


def test_two_writers_converge():
    a, b = SeqNode(rid=0), SeqNode(rid=1)
    for x in "one two three".split():
        a.append(x)
    sync(a, b)
    assert b.items() == ["one", "two", "three"]
    # concurrent edits: a types into the front, b into the back
    a.insert_at(0, "zero")
    b.append("four")
    b.remove_at(1)  # "two"
    sync(a, b)
    assert a.items() == b.items() == ["zero", "one", "three", "four"]
    assert a.idents() == b.idents()


def test_delta_payload_is_tail_only():
    a, b = SeqNode(rid=0), SeqNode(rid=1)
    for i in range(5):
        a.append(f"e{i}")
    sync(a, b)
    a.append("new")
    payload = a.gossip_payload(since=b.version_vector())
    ops = {k: v for k, v in payload.items() if k not in (FLOOR_KEY, FULL_KEY)}
    assert list(ops) == ["0:5"]  # only the unseen op travels
    assert b.receive(payload) == 1
    assert b.items()[-1] == "new"


def test_gc_barrier_prunes_and_delta_still_flows():
    a, b = SeqNode(rid=0), SeqNode(rid=1)
    for i in range(6):
        a.append(f"e{i}")
    sync(a, b)
    b.remove_at(0)
    b.remove_at(0)
    sync(a, b)
    floor = seq_barrier(a, [b.vv_snapshot()])
    assert floor  # all members reachable
    a.collect(floor)
    b.collect(floor)
    # collected: the two removed rows are gone from device AND host records
    assert a.items() == b.items() == [f"e{i}" for i in range(2, 6)]
    assert all("del" not in op for op in a._ops.values())
    assert len(a._ops) == 4  # the four live inserts
    # post-GC delta gossip still works (receiver dominates the floor)
    a.append("tail")
    assert pull(b, a) == 1
    assert b.items()[-1] == "tail"


def test_full_payload_suppresses_stale_live_copy():
    """The resurrection case: c missed the removal, then the collection.
    A full payload + floor adoption must kill c's stale live copy."""
    a, b, c = SeqNode(rid=0), SeqNode(rid=1), SeqNode(rid=2)
    for x in "abc":
        a.append(x)
    sync(a, b)
    sync(a, c)  # c holds all three, live
    b.remove_at(1)  # "b" removed...
    sync(a, b)
    floor = seq_barrier(a, [b.vv_snapshot()])
    a.collect(floor)
    b.collect(floor)  # ...and collected, while c was partitioned away
    assert a.items() == ["a", "c"]
    # c's vv does not dominate a's floor -> full payload + suppression
    payload = a.gossip_payload(since=c.version_vector())
    assert payload.get(FULL_KEY)
    c.receive(payload)
    assert c.items() == ["a", "c"]
    # and the swarm stays converged afterwards
    sync(a, c)
    assert c.items() == ["a", "c"]


def test_snapshot_roundtrip_and_seq_resume():
    a = SeqNode(rid=0)
    for x in "xyz":
        a.append(x)
    a.remove_at(0)
    snap = json.loads(json.dumps(a.to_snapshot()))  # wire-shaped
    b = SeqNode(rid=0)
    b.from_snapshot(snap)
    assert b.items() == a.items()
    assert b.version_vector() == a.version_vector()
    # the restored counter must not re-mint used identities
    ident = b.append("w")
    assert ident == (0, 4)


def test_snapshot_after_collect_restores_floor():
    a, b = SeqNode(rid=0), SeqNode(rid=1)
    for x in "pqr":
        a.append(x)
    sync(a, b)
    a.remove_at(2)
    sync(a, b)
    floor = seq_barrier(a, [b.vv_snapshot()])
    a.collect(floor)
    snap = a.to_snapshot()
    fresh = SeqNode(rid=0)
    fresh.from_snapshot(snap)
    assert fresh.items() == ["p", "q"]
    assert fresh._floor == a._floor
    # a restored node can still serve deltas to a floor-dominating peer
    b.collect(floor)
    assert pull(b, fresh) == 0  # nothing new, but no full fallback crash


def test_receive_widens_to_deep_wire_paths():
    """Daemons with different local depths interoperate: the wire carries
    real levels only, and a receiver widens its table on demand."""
    from crdt_tpu.models import rseq

    n = SeqNode(rid=1, depth=2)
    mid_hi, mid_lo = rseq.split_pos(rseq.MID)
    # a 3-level path (deeper than the table) minted by writer 0
    op = {
        "ins": "deep",
        "path": [[1, 0, 0, 0], [2, 0, 0, 1], [3, 0, 0, 2]],
    }
    assert n.receive({"0:2": op}) == 1
    assert n._depth >= 3
    assert n.items() == ["deep"]
    # and its own shallow edits still join fine afterwards
    n.append("after")
    assert n.items() == ["deep", "after"]


def test_collect_is_all_or_nothing():
    """A node behind the barrier floor adopts nothing (the setnode
    incomparable-floor fix, mirrored here from day one)."""
    a = SeqNode(rid=0)
    a.append("only")
    a.collect({0: 0, 5: 7})  # floor claims knowledge a doesn't have
    assert a._floor == {}
    assert a.metrics._counts["seq_collect_behind"] == 1


@pytest.fixture()
def hosts():
    from crdt_tpu.api.net import NodeHost
    from crdt_tpu.utils.config import ClusterConfig

    cfg = ClusterConfig(delta_gossip=True)
    a = NodeHost(rid=0, peers=[], config=cfg, coordinator=True)
    b = NodeHost(rid=1, peers=[], config=cfg)
    a.agent.peers = [_peer(b)]
    b.agent.peers = [_peer(a)]
    a.start_server()
    b.start_server()
    try:
        yield a, b
    finally:
        a.stop_server()
        b.stop_server()


def _peer(host):
    from crdt_tpu.api.net import RemotePeer

    return RemotePeer(f"http://127.0.0.1:{host.port}")


def _http(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=5) as res:
        return res.status, res.read().decode()


def test_http_surface(hosts):
    a, b = hosts
    code, out = _http(a.url + "/seq/insert", "POST",
                      {"elem": "hello", "index": None})
    assert code == 200 and json.loads(out) == {"rid": 0, "seq": 0}
    _http(a.url + "/seq/insert", "POST", {"elem": "world"})
    code, out = _http(b.url + "/admin/seq_pull", "POST", {"peer": a.url})
    assert code == 200 and json.loads(out)["pulled"]
    code, out = _http(b.url + "/seq")
    assert json.loads(out)["items"] == ["hello", "world"]
    # targeted remove over the wire, then a barrier from the coordinator
    code, out = _http(b.url + "/seq/remove", "POST", {"index": 0})
    got = json.loads(out)
    assert got["removed"] and got["target"] == [0, 0]
    _http(a.url + "/admin/seq_pull", "POST", {"peer": b.url})
    code, out = _http(a.url + "/admin/seq_barrier", "POST", {})
    assert code == 200 and json.loads(out)["floor"]
    code, out = _http(a.url + "/seq")
    assert json.loads(out)["items"] == ["world"]
    # vv surface
    code, out = _http(a.url + "/seq/vv")
    assert code == 200 and "vv" in json.loads(out)


def test_snapshot_restore_keeps_constructor_depth():
    """Round-5 ADVICE fix: a deliberately shallow node must restore at its
    constructor depth (ingest re-widens on demand), not the module default."""
    from crdt_tpu.models import rseq

    a = SeqNode(rid=0, depth=2)
    a.append("a")
    snap = json.loads(json.dumps(a.to_snapshot()))
    b = SeqNode(rid=0, depth=2)
    b.from_snapshot(snap)
    assert b._depth == 2
    assert b.items() == ["a"]
    # default-depth nodes still restore at the default
    c = SeqNode(rid=1)
    c.from_snapshot(json.loads(json.dumps(SeqNode(rid=1).to_snapshot())))
    assert c._depth == rseq.DEPTH


def test_tombstone_index_pruned_by_floor():
    """Round-5 ADVICE fix: _tombstoned entries covered by the floor —
    including suppression-derived identities with no remove record — are
    pruned at floor application, so long-lived nodes stay bounded."""
    a, b, c = SeqNode(rid=0), SeqNode(rid=1), SeqNode(rid=2)
    for x in "abc":
        a.append(x)
    sync(a, b)
    sync(a, c)
    b.remove_at(1)
    sync(a, b)
    floor = seq_barrier(a, [b.vv_snapshot()])
    a.collect(floor)
    b.collect(floor)
    assert a._tombstoned == set()
    assert b._tombstoned == set()
    # the suppression path (full payload to the stale partitioned node)
    # must not leave permanent synthetic entries either
    c.receive(a.gossip_payload(since=c.version_vector()))
    assert c.items() == ["a", "c"]
    assert c._tombstoned == set()
