"""Unit tests for the sorted-segment union op (the TPU replacement for the
reference's two-pointer log union, /root/reference/main.go:49-73)."""
import jax.numpy as jnp
import numpy as np

from crdt_tpu.ops import sorted_union as su
from crdt_tpu.utils.constants import SENTINEL_PY


def _mk(keys, vals, cap):
    """Build sorted sentinel-padded single-column keyed arrays."""
    n = len(keys)
    pad = cap - n
    k = np.asarray(sorted(keys) + [SENTINEL_PY] * pad, np.int32)
    order = np.argsort(keys, kind="stable")
    v = np.asarray([vals[i] for i in order] + [0] * pad, np.int32)
    return (jnp.asarray(k),), jnp.asarray(v)


def test_disjoint_union():
    ka, va = _mk([1, 5], [10, 50], 4)
    kb, vb = _mk([2, 9], [20, 90], 4)
    keys, vals, n = su.sorted_union(ka, va, kb, vb, out_size=8)
    assert int(n) == 4
    assert list(np.asarray(keys[0])[:4]) == [1, 2, 5, 9]
    assert list(np.asarray(vals)[:4]) == [10, 20, 50, 90]
    assert all(np.asarray(keys[0])[4:] == SENTINEL_PY)


def test_duplicate_keeps_first_side():
    ka, va = _mk([3, 7], [1, 2], 4)
    kb, vb = _mk([3, 8], [99, 3], 4)
    keys, vals, n = su.sorted_union(ka, va, kb, vb, out_size=8)
    assert int(n) == 3
    assert list(np.asarray(keys[0])[:3]) == [3, 7, 8]
    # local (a-side) value wins on the duplicate key 3
    assert list(np.asarray(vals)[:3]) == [1, 2, 3]


def test_duplicate_custom_combine():
    ka, va = _mk([3], [4], 2)
    kb, vb = _mk([3], [8], 2)
    _, vals, n = su.sorted_union(ka, va, kb, vb, combine=lambda x, y: x | y, out_size=4)
    assert int(n) == 1
    assert int(np.asarray(vals)[0]) == 12


def test_multicolumn_keys_tie_on_first():
    # same first column, distinct second column -> two distinct keys
    ka = (jnp.asarray([5, SENTINEL_PY], jnp.int32), jnp.asarray([0, SENTINEL_PY], jnp.int32))
    kb = (jnp.asarray([5, SENTINEL_PY], jnp.int32), jnp.asarray([1, SENTINEL_PY], jnp.int32))
    va = jnp.asarray([10, 0], jnp.int32)
    vb = jnp.asarray([11, 0], jnp.int32)
    keys, vals, n = su.sorted_union(ka, va, kb, vb)
    assert int(n) == 2
    assert list(np.asarray(keys[0])[:2]) == [5, 5]
    assert list(np.asarray(keys[1])[:2]) == [0, 1]
    assert list(np.asarray(vals)[:2]) == [10, 11]


def test_out_size_truncates_largest():
    ka, va = _mk([1, 2], [1, 2], 2)
    kb, vb = _mk([3, 4], [3, 4], 2)
    keys, vals, n = su.sorted_union(ka, va, kb, vb, out_size=3)
    assert int(n) == 4  # true union size still reported
    assert list(np.asarray(keys[0])) == [1, 2, 3]


def test_against_python_set_semantics():
    rng = np.random.default_rng(0)
    for _ in range(25):
        cap = 16
        a = {int(k): int(v) for k, v in zip(rng.choice(100, 8, replace=False), rng.integers(0, 50, 8))}
        b = {int(k): int(v) for k, v in zip(rng.choice(100, 8, replace=False), rng.integers(0, 50, 8))}
        ka, va = _mk(list(a), [a[k] for k in a], cap)
        kb, vb = _mk(list(b), [b[k] for k in b], cap)
        keys, vals, n = su.sorted_union(ka, va, kb, vb)
        expect = dict(b)
        expect.update(a)  # a wins duplicates
        got_keys = [int(k) for k in np.asarray(keys[0]) if k != SENTINEL_PY]
        got = {k: int(v) for k, v in zip(got_keys, np.asarray(vals))}
        assert int(n) == len(expect)
        assert got == expect
