"""Scaled parity fuzz (VERDICT round 1 #5): the bit-exactness claims get
hundreds of seeds and long schedules behind a ``--long`` knob (or
``CRDT_LONG=1``); the default CI schedule stays small and fast.

Three independent surfaces, layered so nothing is circular:

1. device vs oracle      — the TPU OpLog path against the quirks-OFF oracle
                           (the fixed semantics), mid-schedule and at the end;
2. HTTP shim vs oracle   — the quirks-ON HTTP server against a directly-
                           driven quirks-ON oracle mirror (pins the wire
                           codec + HTTP layer; the oracle itself is pinned
                           against main.go by tests/test_go_golden.py);
3. quirk metamorphics    — signature properties each quirk must exhibit
                           under random schedules (every quirk stays
                           load-bearing, SURVEY.md §0.1).

Long-mode results are recorded in PARITY.md.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from crdt_tpu.oracle import OracleReplica, Quirks
from crdt_tpu.utils.clock import ManualClock
from crdt_tpu.utils.intern import Interner

from tests.test_parity import ALPHABET, DeviceReplica, _rand_cmd


def pytest_generate_tests(metafunc):
    long = metafunc.config.getoption("--long")
    if "fuzz_seed" in metafunc.fixturenames:
        metafunc.parametrize("fuzz_seed", range(50 if long else 2))
    if "shim_seed" in metafunc.fixturenames:
        metafunc.parametrize("shim_seed", range(25 if long else 2))
    if "quirk_seed" in metafunc.fixturenames:
        metafunc.parametrize("quirk_seed", range(20 if long else 3))


@pytest.fixture
def long_mode(request):
    return bool(request.config.getoption("--long"))


# ---- 1. device vs oracle, scaled --------------------------------------------


def test_device_oracle_fuzz(fuzz_seed, long_mode):
    """The round-1 schedule (3 seeds x 40 writes x 4 replicas) at fuzz
    scale: 50 seeds x 500 writes x 6 replicas in long mode, with parity
    asserted EVERY 50 writes on a random replica (not only at the end) and
    a final all-replica check."""
    rng = np.random.default_rng(1000 + fuzz_seed)
    n_replicas = 6 if long_mode else 4
    n_writes = 500 if long_mode else 60
    capacity = 2048 if long_mode else 256
    keys, values = Interner(), Interner()
    dev = [DeviceReplica(r, capacity, keys, values) for r in range(n_replicas)]
    ora = [OracleReplica(r, Quirks()) for r in range(n_replicas)]

    ts = 0
    for w in range(n_writes):
        ts += int(rng.integers(0, 3))  # same-ms collisions stay common
        r = int(rng.integers(0, n_replicas))
        cmd = _rand_cmd(rng, multi_key_p=0.3, non_num_p=0.2, odd_num_p=0.15)
        dev[r].add_command(cmd, ts)
        ora[r].add_command(cmd, ts)
        if rng.random() < 0.25:  # random gossip pull
            dst, src = rng.choice(n_replicas, size=2, replace=False)
            dev[dst].receive(dev[src].log)
            ora[dst].receive(ora[src].gossip_payload())
        if w % 50 == 49:  # mid-schedule spot check
            r = int(rng.integers(0, n_replicas))
            assert dev[r].materialized() == ora[r].rebuilt_state(), (
                f"replica {r} diverged at write {w} (seed {fuzz_seed})"
            )

    for r in range(n_replicas):
        assert dev[r].materialized() == ora[r].rebuilt_state(), f"replica {r}"


# ---- 2. HTTP shim vs in-process oracle mirror -------------------------------


def _req(url, method="GET", data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=5) as res:
            return res.status, res.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_shim_oracle_mirror_fuzz(shim_seed, long_mode):
    """Drive the quirks-ON HTTP cluster with a random schedule (valid
    writes, invalid bodies, gossip pulls, state reads) while applying the
    SAME schedule to directly-held oracle replicas; every read must match
    byte-for-byte and every handler outcome must agree.  This pins the HTTP
    layer + Go-wire JSON codec against the in-process oracle under far more
    schedules than the golden fixtures cover."""
    from crdt_tpu.oracle.shim import OracleHttpCluster, go_json_dumps

    rng = np.random.default_rng(2000 + shim_seed)
    n = 3
    steps = 400 if long_mode else 60
    clock = ManualClock(start=1_000_000)
    cluster = OracleHttpCluster(n=n, clock=clock)
    cluster.start()
    mirror = [OracleReplica(rid=i, quirks=Quirks.reference()) for i in range(n)]
    try:
        for _ in range(steps):
            clock.advance(int(rng.integers(0, 2)))  # same-ms collisions too
            i = int(rng.integers(0, n))
            x = rng.random()
            if x < 0.5:  # write (sometimes invalid)
                if rng.random() < 0.1:
                    body, cmd = b"not json", None
                else:
                    cmd = _rand_cmd(rng, multi_key_p=0.3)
                    body = json.dumps(cmd).encode()
                status, got = _req(cluster.urls[i] + "/data", "POST", body)
                want = mirror[i].add_command(
                    dict(cmd) if cmd is not None else None, ts=clock.now_ms()
                )
                assert (status, got.decode()) == (want.status, want.body)
            elif x < 0.75:  # gossip pull dst <- src
                dst, src = rng.choice(n, size=2, replace=False)
                ok = cluster.gossip_once(int(dst), int(src))
                assert ok
                mirror[dst].receive(mirror[src].gossip_payload())
            else:  # read
                status, got = _req(cluster.urls[i] + "/data")
                assert status == 200
                assert got.decode() == go_json_dumps(mirror[i].state)
        for i in range(n):
            _, got = _req(cluster.urls[i] + "/gossip")
            assert got.decode() == go_json_dumps(
                {str(k[0]): cmd for k, (cmd, _) in sorted(mirror[i].log.items())}
            )
    finally:
        cluster.stop()


# ---- 3. quirk metamorphics --------------------------------------------------


ALL_QUIRKS = (
    "local_op_exclusion", "ts_only_keys", "tail_drop",
    "multikey_early_return", "handler_error_return",
)


def _rand_schedule(rng, replicas, steps):
    """Apply a random write/gossip schedule; returns nothing (mutates)."""
    ts = 0
    for _ in range(steps):
        ts += int(rng.integers(0, 3))
        r = int(rng.integers(0, len(replicas)))
        if rng.random() < 0.6:
            replicas[r].add_command(_rand_cmd(rng, multi_key_p=0.3), ts=ts)
        elif len(replicas) > 1:
            dst, src = rng.choice(len(replicas), size=2, replace=False)
            replicas[dst].receive(replicas[src].gossip_payload())


def test_quirk_combination_metamorphics(quirk_seed, long_mode):
    """Random quirk subsets under random schedules: determinism (replaying
    the identical schedule reproduces byte-identical logs+states) plus each
    enabled quirk's signature property."""
    rng = np.random.default_rng(3000 + quirk_seed)
    steps = 300 if long_mode else 60
    subset = {q: bool(rng.integers(0, 2)) for q in ALL_QUIRKS}
    quirks = Quirks(**subset)

    def build():
        rng2 = np.random.default_rng(9000 + quirk_seed)
        reps = [OracleReplica(r, Quirks(**subset)) for r in range(3)]
        _rand_schedule(rng2, reps, steps)
        return reps

    a, b = build(), build()
    # determinism: identical schedule -> identical observable state
    for x, y in zip(a, b):
        assert x.log == y.log
        assert x.rebuilt_state() == y.rebuilt_state()

    r0 = a[0]
    if quirks.ts_only_keys:
        assert all(len(k) == 1 for k in r0.log)  # bare-ms identity (§0.1.2)
    else:
        assert all(len(k) == 3 for k in r0.log)
    if quirks.tail_drop and r0.log:
        # a payload strictly newer than everything local is fully dropped
        top = max(r0.log)
        probe_key = (top[0] + 1000,) if quirks.ts_only_keys else (
            top[0] + 1000, 99, 0)
        before = dict(r0.log)
        r0.receive({probe_key: {"zz": "1"}})
        assert r0.log == before  # nothing adopted (main.go:49)
    if not quirks.tail_drop:
        # full union: everything the peer has is adopted
        r1 = a[1]
        r1.receive(r0.gossip_payload())
        assert set(r0.log) <= set(r1.log)
    if quirks.local_op_exclusion:
        # after any merge, a replica's own (pointer) entries never count
        r2 = a[2]
        r2.add_command({"own": "5"}, ts=10**7)
        r2.receive(a[0].gossip_payload())  # any merge triggers the rebuild
        assert "own" not in r2.state or r2.state["own"] != "5" or any(
            cmd is not None and "own" in cmd
            for k, (cmd, is_local) in r2.log.items() if not is_local
        )
