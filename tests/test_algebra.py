"""Combinator-layer tests: the compositional CRDT algebra.

The registry-wide ACI sweep (tests/test_lattice_laws.py) already law-checks
every registered composite; this file pins what the sweep can't see —
combinator *semantics* (dominance, reset, map parity), metadata
propagation, the act laws of the semidirect construction, and the
bit-equivalence of ``mapof(pncounter)`` against the bespoke ``ormap``
merge on randomized op traces (the acceptance criterion of the algebra
ISSUE)."""
import numpy as np
import pytest

from crdt_tpu.models import (
    composite,
    gcounter,
    lww,
    mvregister,
    ormap,
    pncounter,
)
from crdt_tpu.models.composite import Pair
from crdt_tpu.ops import algebra, joins
from crdt_tpu.ops import randstate as rs
from tests.helpers import tree_equal


def _spec(name):
    return joins.registered_joins()[name]


# ------------------------------------------------------------- metadata


def test_composites_registered_with_propagated_metadata():
    mapof_pn = _spec("mapof(pncounter)")
    assert mapof_pn.parts == ("pncounter",)
    assert mapof_pn.structurally_commutative  # inner claims True

    lex = _spec("lexicographic(lww,mvregister)")
    assert lex.parts == ("lww", "mvregister")
    assert not lex.structurally_commutative  # selects: always False

    semi = _spec("semidirect(gcounter,pncounter)")
    assert semi.parts == ("gcounter", "pncounter")
    assert not semi.structurally_commutative  # action: always False

    prod = _spec("product(gcounter,pncounter)")
    assert prod.parts == ("gcounter", "pncounter")
    assert prod.structurally_commutative  # AND of two True parts


def test_product_claim_is_and_of_parts():
    """product over a non-commutative-claiming part claims False."""
    name = "product(gcounter,lww)"
    try:
        spec = algebra.product("gcounter", "lww")
        assert spec.name == name
        assert not spec.structurally_commutative
        assert spec.parts == ("gcounter", "lww")
        # derived neutral and rand came from the parts
        n = spec.neutral()
        assert tree_equal(n.fst, gcounter.zero(8))
        assert tree_equal(n.snd, lww.zero())
        assert tree_equal(spec.join(n, n), n)
    finally:
        joins._JOIN_REGISTRY.pop(name, None)


def test_resolve_unknown_part_raises():
    with pytest.raises(KeyError):
        algebra.product("pncounter", "no_such_lattice")


# ----------------------------------------------------- mapof <-> ormap


def _rand_trace(rng, n_ops, n_keys, n_writers):
    ops = []
    for _ in range(n_ops):
        key = int(rng.integers(0, n_keys))
        writer = int(rng.integers(0, n_writers))
        if rng.random() < 0.25:
            ops.append(("rem", key, writer, 0))
        else:
            ops.append(("upd", key, writer, int(rng.integers(-9, 10))))
    return ops


def _apply_trace(state, ops):
    for op, key, writer, delta in ops:
        if op == "rem":
            state = ormap.remove(state, key, writer)
        else:
            state = ormap.update(
                state, key, writer,
                lambda v, _w=writer, _d=delta: pncounter.add(v, _w, _d))
    return state


def test_mapof_pncounter_matches_bespoke_ormap_on_random_traces():
    """The composed join must be bit-equivalent to the bespoke ormap merge
    (`ormap.joiner`) on states built from randomized op traces, and the
    materialized view (contains + per-key counter values) must agree."""
    spec = _spec("mapof(pncounter)")
    n_keys, n_writers = 4, 3
    bespoke = ormap.joiner(pncounter.join)  # elementwise: batches as-is
    rng = np.random.default_rng(42)
    for _ in range(10):
        empty = ormap.empty(n_keys, n_writers, pncounter.zero(n_writers))
        a = _apply_trace(empty, _rand_trace(rng, 12, n_keys, n_writers))
        b = _apply_trace(empty, _rand_trace(rng, 12, n_keys, n_writers))
        got = spec.join(a, b)
        want = bespoke(a, b)
        assert tree_equal(got, want), "composed join != bespoke ormap merge"
        assert np.array_equal(
            np.asarray(ormap.contains(got)), np.asarray(ormap.contains(want)))
        assert np.array_equal(
            np.asarray(pncounter.value(got.values)),
            np.asarray(pncounter.value(want.values)))


def test_mapof_join_is_shape_generic():
    """The registered join serves ANY key/writer universe, not just the
    example's — the servable CompositeNode relies on this as it grows."""
    spec = _spec("mapof(pncounter)")
    n_keys, n_writers = 6, 2
    empty = ormap.empty(n_keys, n_writers, pncounter.zero(n_writers))
    a = ormap.update(empty, 5, 1, lambda v: pncounter.add(v, 1, 7))
    b = ormap.update(empty, 0, 0, lambda v: pncounter.add(v, 0, -2))
    m = spec.join(a, b)
    assert list(np.asarray(ormap.contains(m))) == [
        True, False, False, False, False, True]
    assert list(np.asarray(pncounter.value(m.values))) == [-2, 0, 0, 0, 0, 7]


# ------------------------------------------------------- lexicographic


def test_lexicographic_dominance_and_tiebreak():
    reg_hi = lww.write(lww.zero(), ts=20, rid=1, payload=7)
    reg_lo = lww.write(lww.zero(), ts=10, rid=2, payload=8)
    mv_a = mvregister.write(mvregister.zero(4), writer=0, ts=20, payload=70)
    mv_b = mvregister.write(mvregister.zero(4), writer=1, ts=10, payload=80)
    spec = _spec("lexicographic(lww,mvregister)")

    # strictly greater rank takes BOTH parts wholesale — the losing side's
    # mv-plane (siblings of a superseded era) does not leak through
    out = spec.join(Pair(fst=reg_hi, snd=mv_a), Pair(fst=reg_lo, snd=mv_b))
    assert tree_equal(out.fst, reg_hi)
    assert tree_equal(out.snd, mv_a)
    # ... and symmetrically
    out2 = spec.join(Pair(fst=reg_lo, snd=mv_b), Pair(fst=reg_hi, snd=mv_a))
    assert tree_equal(out2, out)

    # equal rank (identical winning write): the b-parts join — concurrent
    # siblings of the same era surface together
    tie = spec.join(Pair(fst=reg_hi, snd=mv_a), Pair(fst=reg_hi, snd=mv_b))
    assert tree_equal(tie.fst, reg_hi)
    assert tree_equal(tie.snd, mvregister.join(mv_a, mv_b))
    assert int(mvregister.n_siblings(tie.snd)) == 2


# ----------------------------------------------------------- semidirect


def test_semidirect_epoch_reset_counter():
    spec = _spec("semidirect(gcounter,pncounter)")
    zero = spec.neutral()
    # replica A counts 5 in epoch 0; replica B bumps the epoch then counts 3
    a = composite.epoch_add(zero, node=0, amount=5)
    b = composite.epoch_add(composite.epoch_bump(zero, node=1), node=1,
                            amount=3)
    merged = spec.join(a, b)
    # A's epoch-0 contribution was transported into epoch 1 => reset
    assert int(composite.epoch_value(merged)) == 3
    # same-epoch contributions keep merging normally
    c = composite.epoch_add(merged, node=0, amount=4)
    assert int(composite.epoch_value(spec.join(merged, c))) == 7
    # a stale replica that never saw the bump keeps being reset on merge
    assert int(composite.epoch_value(spec.join(c, a))) == 7


def test_semidirect_act_laws():
    """The three laws semidirect requires of ``act`` (algebra docstring):
    identity, composition along monotone frame chains, join-homomorphism."""
    rng = np.random.default_rng(9)
    act = composite.reset_act
    for _ in range(20):
        f1 = rs.rand_gcounter(rng)
        f2 = gcounter.join(f1, rs.rand_gcounter(rng))   # f1 <= f2
        f3 = gcounter.join(f2, rs.rand_gcounter(rng))   # f2 <= f3
        b1, b2 = rs.rand_pncounter(rng), rs.rand_pncounter(rng)
        assert tree_equal(act(f1, f1, b1), b1), "identity"
        assert tree_equal(
            act(f3, f2, act(f2, f1, b1)), act(f3, f1, b1)), "composition"
        assert tree_equal(
            act(f3, f1, pncounter.join(b1, b2)),
            pncounter.join(act(f3, f1, b1), act(f3, f1, b2)),
        ), "join-homomorphism"


# ----------------------------------------------- registry-driven driving


def test_converge_composite_from_registry():
    """A composite converges a stacked swarm straight from the registry —
    no caller-threaded neutral, no bespoke batched join."""
    spec = _spec("mapof(pncounter)")
    rng = np.random.default_rng(3)
    states = [spec.rand(rng) for _ in range(5)]
    import jax

    swarm = jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                         *states)
    out = joins.converge("mapof(pncounter)", swarm)
    # every replica landed on the same least upper bound
    first = jax.tree.map(lambda x: x[0], out)
    for i in range(1, 5):
        assert tree_equal(jax.tree.map(lambda x, _i=i: x[_i], out), first)
    # and the LUB dominates every input (join absorbs each state)
    for s in states:
        assert tree_equal(spec.join(first, s), first)
