"""Checkpoint/resume: node snapshots restore bit-exact state; swarm
snapshots round-trip; a restored node keeps gossiping correctly."""
import jax
import jax.numpy as jnp
import numpy as np

from crdt_tpu.api.cluster import LocalCluster
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.models import gcounter
from crdt_tpu.utils import checkpoint
from crdt_tpu.utils.config import ClusterConfig
from tests.helpers import tree_equal


def test_node_snapshot_roundtrip(tmp_path):
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "5", "s": "hello"}, ts=10)
    n.add_command({"x": "-2"}, ts=11)
    checkpoint.save_node(tmp_path / "snap", n)

    n2 = ReplicaNode(rid=0, capacity=32)
    checkpoint.restore_node(tmp_path / "snap", n2)
    assert n2.get_state() == n.get_state() == {"x": "3", "s": "hello"}
    assert n2.gossip_payload() == n.gossip_payload()
    assert tree_equal(n2.log, n.log)


def test_restored_node_rejoins_cluster(tmp_path):
    cfg = ClusterConfig(n_replicas=3, log_capacity=32)
    c = LocalCluster(cfg)
    for i, node in enumerate(c.nodes):
        node.add_command({"abc"[i]: "2"}, ts=50 + i)
    checkpoint.save_node(tmp_path / "n1", c.nodes[1])

    # "crash" node 1: fresh process state, restore from snapshot
    c.nodes[1] = ReplicaNode(rid=1, capacity=32, metrics=c.metrics)
    checkpoint.restore_node(tmp_path / "n1", c.nodes[1])
    for _ in range(60):
        c.tick()
        if c.converged():
            break
    assert c.converged()
    assert c.nodes[1].get_state() == {"a": "2", "b": "2", "c": "2"}


def test_swarm_snapshot_roundtrip(tmp_path):
    state = gcounter.GCounter(
        counts=jnp.asarray(np.arange(64, dtype=np.int32).reshape(8, 8))
    )
    checkpoint.save_swarm(tmp_path / "swarm", state)
    like = gcounter.zero(8, batch=(8,))
    restored = checkpoint.restore_swarm(tmp_path / "swarm", like)
    assert tree_equal(restored, state)


def test_restore_preserves_seq_identity(tmp_path):
    """A restored node must not mint an already-used (ts, rid, seq): write
    at a pinned timestamp, snapshot, restore, write again at the SAME
    timestamp — both ops must survive."""
    from crdt_tpu.utils.clock import ManualClock

    clock = ManualClock(start=5)
    n = ReplicaNode(rid=0, capacity=32, clock=clock)
    n.add_command({"x": "1"})
    checkpoint.save_node(tmp_path / "s", n)

    n2 = ReplicaNode(rid=0, capacity=32, clock=ManualClock(start=5))
    checkpoint.restore_node(tmp_path / "s", n2)
    assert n2.add_command({"x": "1"})  # same ts=5, must get a fresh seq
    assert n2.get_state() == {"x": "2"}
