"""Driver-contract tests: entry() compile-checks under jit; dryrun_multichip
runs on the 8-virtual-device CPU mesh exactly as the driver invokes it."""
import jax
import numpy as np

import __graft_entry__ as ge


def test_entry_jits_and_runs():
    fn, args = ge.entry()
    out_state, kv = jax.jit(fn)(*args)
    jax.block_until_ready((out_state, kv))
    assert np.asarray(kv.present).shape == (8, 16)


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


def test_dryrun_multichip_nonpow2():
    ge.dryrun_multichip(6)
