"""Driver-contract tests: entry() compile-checks under jit; dryrun_multichip
runs on the 8-virtual-device CPU mesh exactly as the driver invokes it."""
import jax
import numpy as np
import pytest

import __graft_entry__ as ge

# jax < 0.5 has no jax_num_cpu_devices option, so dryrun_multichip cannot
# raise the virtual CPU device count past 1 and the mesh builds fail
_HAS_CPU_MESH = "jax_num_cpu_devices" in jax.config._value_holders
multichip = pytest.mark.skipif(
    not _HAS_CPU_MESH,
    reason="jax %s lacks jax_num_cpu_devices (needs >= 0.5 for virtual "
           "CPU multichip meshes)" % jax.__version__)


def test_entry_jits_and_runs():
    fn, args = ge.entry()
    out_state, kv = jax.jit(fn)(*args)
    jax.block_until_ready((out_state, kv))
    assert np.asarray(kv.present).shape == (8, 16)


@multichip
def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


@multichip
def test_dryrun_multichip_nonpow2():
    ge.dryrun_multichip(6)
