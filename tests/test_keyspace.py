"""Sharded keyspace tier tests: rendezvous routing PROPERTIES (the
three the tier leans on — cross-process determinism, balance, minimal
remap under membership change), two-level key qualification, shard
routing agreement across independently built keyspaces, shard-scoped
anti-entropy, and the tenant door's quota-slice isolation + labeled
shed/quarantine provenance.

The determinism test spawns a REAL subprocess with a different
PYTHONHASHSEED: rendezvous owners must come out identical, which is
exactly what builtin hash() would fail (it is salted per process) and
why routing.py scores with blake2b.
"""
from __future__ import annotations

import collections
import json
import pathlib
import subprocess
import sys

import pytest

from crdt_tpu.ingest import PageBuilder, PageFormatError, ShedError
from crdt_tpu.ingest.shed import ShedPolicy
from crdt_tpu.keyspace import (KeyspaceFrontDoor, ShardedKeyspace,
                               TENANT_LANE, qualify, route_key,
                               split_qualified, validate_tenant)
from crdt_tpu.keyspace.routing import RendezvousRouter, ranked_members
from crdt_tpu.obs.events import EventLog
from crdt_tpu.utils.config import ClusterConfig

ROUTING_PY = str(pathlib.Path(__file__).resolve().parent.parent
                 / "crdt_tpu" / "keyspace" / "routing.py")


def _keys(n: int, prefix: str = "u") -> list:
    return [f"{prefix}{i:06d}" for i in range(n)]


# ---- routing properties ----

def test_route_key_unambiguous_and_tenant_validation():
    # ("ab", "c") vs ("a", "bc") must never alias
    assert route_key("ab", "c") != route_key("a", "bc")
    assert validate_tenant("t-acme") == "t-acme"
    for bad in (None, "", 7, "with:colon", "ctrl\x01char", "nl\nname"):
        with pytest.raises(ValueError):
            validate_tenant(bad)


def test_rendezvous_deterministic_across_processes():
    """Owners computed in a subprocess with a DIFFERENT hash seed match
    this process exactly — routing is a pure function of (members, key),
    never of interpreter state."""
    members = [f"shard-{i}" for i in range(5)]
    keys = _keys(64)
    local = [RendezvousRouter(members).owner_index(k) for k in keys]
    # import routing.py by file path: the subprocess pins the hash, not
    # the package's jax import time
    code = (
        "import importlib.util, json, sys\n"
        f"spec = importlib.util.spec_from_file_location('r', {ROUTING_PY!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        f"r = mod.RendezvousRouter({members!r})\n"
        f"print(json.dumps([r.owner_index(k) for k in {keys!r}]))\n"
    )
    for seed in ("0", "4242"):
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={"PYTHONHASHSEED": seed, "PATH": "/usr/bin:/bin"},
            check=True)
        assert json.loads(out.stdout) == local, f"PYTHONHASHSEED={seed}"


def test_rendezvous_balance():
    """No pathological shard: every member owns ~K/n of a uniform key
    population (binomial stddev for K=4096, n=4 is ~27 keys; the ±20%
    band is over 7 sigma)."""
    n, keys = 4, _keys(4096)
    router = RendezvousRouter([f"shard-{i}" for i in range(n)])
    counts = collections.Counter(router.owner(k) for k in keys)
    ideal = len(keys) / n
    assert len(counts) == n
    for member, c in counts.items():
        assert 0.8 * ideal <= c <= 1.2 * ideal, (
            f"{member} owns {c} keys (ideal {ideal:.0f})")


def test_rendezvous_minimal_remap_on_join():
    """Adding a member moves ONLY the keys the new member now wins —
    ~K/(n+1) of them — and every moved key lands on the new member."""
    keys = _keys(3000)
    before = RendezvousRouter([f"shard-{i}" for i in range(5)])
    after = before.with_member("shard-5")
    moved = [k for k in keys if before.owner(k) != after.owner(k)]
    assert all(after.owner(k) == "shard-5" for k in moved), (
        "a key moved between OLD members on join — HRW argmax broken")
    expected = len(keys) / 6
    assert 0.7 * expected <= len(moved) <= 1.3 * expected, (
        f"{len(moved)} keys moved, expected ~{expected:.0f}")


def test_rendezvous_minimal_remap_on_leave():
    """Removing a member moves ONLY its own keys; each falls to its
    second-ranked member."""
    keys = _keys(2000)
    before = RendezvousRouter([f"shard-{i}" for i in range(5)])
    after = before.without_member("shard-2")
    for k in keys:
        owner = before.owner(k)
        if owner == "shard-2":
            assert after.owner(k) == before.ranked(k)[1]
        else:
            assert after.owner(k) == owner, (
                f"{k} moved off surviving member {owner}")


def test_rendezvous_ranked_and_member_hygiene():
    router = RendezvousRouter(["a", "b", "c"])
    for k in _keys(32):
        ranked = router.ranked(k)
        assert ranked[0] == router.owner(k)
        assert sorted(ranked) == ["a", "b", "c"]
        assert router.ranked(k, 2) == ranked[:2]
    with pytest.raises(ValueError):
        RendezvousRouter([])
    with pytest.raises(ValueError):
        RendezvousRouter(["a", "a"])
    with pytest.raises(ValueError):
        router.without_member("nope")


def test_ranked_members_is_the_shared_rendezvous_seam():
    """Cross-use determinism: the module-level ``ranked_members`` (what
    the consistency plane's coordinator-lease routing ranks LIVE NODE
    URLS with) and ``RendezvousRouter.ranked`` (what the keyspace ranks
    shard names with) are ONE function — same members + same key ->
    same ranking, whatever the member strings look like."""
    member_sets = (
        [f"shard-{i}" for i in range(6)],
        [f"http://127.0.0.1:{8000 + i}" for i in range(5)],
    )
    for members in member_sets:
        router = RendezvousRouter(members)
        for k in _keys(48) + [f"lease-slot-{s}" for s in range(8)]:
            assert router.ranked(k) == ranked_members(members, k)
            assert router.owner(k) == ranked_members(members, k, 1)[0]
    # ident-based ranking: weight over the STABLE name, returned values
    # stay the member strings — two fleets whose ephemeral URLs map to
    # the same member names route identically (what lets the nemesis
    # soak replay byte-identically across OS-assigned ports)
    urls_a = [f"http://127.0.0.1:{7000 + i}" for i in range(4)]
    urls_b = [f"http://127.0.0.1:{9100 + i}" for i in range(4)]
    ident_a = {u: f"member-{i}" for i, u in enumerate(urls_a)}
    ident_b = {u: f"member-{i}" for i, u in enumerate(urls_b)}
    for k in [f"lease-slot-{s}" for s in range(8)]:
        ra = ranked_members(urls_a, k, ident=ident_a.get)
        rb = ranked_members(urls_b, k, ident=ident_b.get)
        assert [ident_a[m] for m in ra] == [ident_b[m] for m in rb]
        # and ident=None stays byte-compatible with the router
        assert ranked_members(urls_a, k, ident=None) == \
            RendezvousRouter(urls_a).ranked(k)


# ---- qualified keys & shard routing ----

def test_qualify_split_roundtrip():
    for tenant, key in (("t", "k"), ("t-acme", "a:b:c"), ("x", "")):
        assert split_qualified(qualify(tenant, key)) == (tenant, key)


def test_shard_routing_agrees_across_instances():
    """Two independently built keyspaces (different rids — different
    NODES) route every tenant key identically: the property that makes
    per-shard convergence fleet convergence."""
    a = ShardedKeyspace(rid=0, n_shards=8, capacity=64)
    b = ShardedKeyspace(rid=3, n_shards=8, capacity=64)
    for tenant in ("t-acme", "t-bolt"):
        for key in _keys(128):
            assert a.shard_of(tenant, key) == b.shard_of(tenant, key)


def test_shard_scoped_gossip_converges_and_is_idempotent():
    ks = ShardedKeyspace(rid=0, n_shards=4, capacity=64)
    door = KeyspaceFrontDoor(ks, max_batch=8)
    for i in range(24):
        assert door.admit_kv("t-acme", f"k{i}", f"v{i}", timeout=5.0)
    twin = ShardedKeyspace(rid=1, n_shards=4, capacity=64)
    for i in range(4):
        payload = ks.gossip_payload(i, None)
        twin.receive(i, payload)
        twin.receive(i, payload)  # duplicate delivery: CRDT no-op
        assert twin.shards[i].get_state() == ks.shards[i].get_state()
        assert twin.version_vector(i) == ks.version_vector(i)
    assert twin.tenant_state("t-acme") == {
        f"k{i}": f"v{i}" for i in range(24)}


# ---- tenant door: isolation, quota slices, labeled provenance ----

def test_door_tenant_views_are_disjoint():
    ks = ShardedKeyspace(rid=0, n_shards=4, capacity=64)
    door = KeyspaceFrontDoor(ks, max_batch=4)
    door.admit_cmd("t-acme", {"shared-key": "acme", "a1": "1"}, timeout=5.0)
    door.admit_cmd("t-bolt", {"shared-key": "bolt", "b1": "2"}, timeout=5.0)
    assert ks.tenant_state("t-acme") == {"shared-key": "acme", "a1": "1"}
    assert ks.tenant_state("t-bolt") == {"shared-key": "bolt", "b1": "2"}
    assert ks.get("t-acme", "shared-key") == "acme"
    assert ks.get("t-bolt", "shared-key") == "bolt"


def test_tenant_quota_shed_is_labeled_and_isolated():
    """A noisy tenant's burst sheds on ITS quota slice — tenant-labeled
    counters and black-box event — while a neighbor keeps writing
    through the very same lanes."""
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=64)
    policy = ShedPolicy(high_water=1024,
                        tenant_high_water={"t-noisy": 2})
    events = EventLog(node="0")
    door = KeyspaceFrontDoor(ks, max_batch=4, policy=policy, node="0",
                             events=events)
    with pytest.raises(ShedError) as ei:
        door.admit_cmd("t-noisy", {f"k{i}": "v" for i in range(3)},
                       timeout=5.0)
    err = ei.value
    assert err.tenant == "t-noisy"
    assert err.lane == TENANT_LANE
    assert err.high_water == 2 and err.retry_after_s > 0
    # the neighbor is untouched by the noisy tenant's shed
    assert door.admit_kv("t-acme", "k", "v", timeout=5.0) is not None
    # within-quota noisy writes still land
    door.admit_cmd("t-noisy", {"k0": "v"}, timeout=5.0)
    reg = door.metrics.registry
    assert reg.counter_value("ingest_shed", lane=TENANT_LANE, node="0",
                             tenant="t-noisy") == 1
    assert reg.counter_value("ingest_shed_ops", lane=TENANT_LANE,
                             node="0", tenant="t-noisy") == 3
    sheds = events.find(event="ingest_shed")
    assert len(sheds) == 1
    assert sheds[0]["tenant"] == "t-noisy"
    assert sheds[0]["lane"] == TENANT_LANE
    assert sheds[0]["high_water"] == 2


def test_page_quarantine_is_tenant_labeled_and_whole():
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=64)
    events = EventLog(node="0")
    door = KeyspaceFrontDoor(ks, max_batch=8, node="0", events=events)
    pager = PageBuilder(origin=7, page_size=1 << 16)
    for i in range(4):
        pager.add(f"k{i}", "v")
    raw = bytearray(pager.flush())
    raw[len(raw) // 2] ^= 0xFF  # corrupt the body: checksum must catch
    with pytest.raises(PageFormatError):
        door.admit_page(bytes(raw), "t-acme", timeout=5.0)
    reg = door.metrics.registry
    assert reg.counter_value("ingest_pages_quarantined", node="0",
                             tenant="t-acme") == 1
    quars = events.find(event="ingest_page_quarantine")
    assert len(quars) == 1 and quars[0]["tenant"] == "t-acme"
    # nothing from the poisoned page leaked into any shard
    assert ks.state() == {}


def test_page_admission_fans_out_and_dedups():
    ks = ShardedKeyspace(rid=0, n_shards=4, capacity=64)
    door = KeyspaceFrontDoor(ks, max_batch=64, node="0")
    pager = PageBuilder(origin=7, page_size=1 << 16)
    for i in range(16):
        pager.add(f"k{i}", f"v{i}")
    raw = pager.flush()
    res = door.admit_page(raw, "t-acme", timeout=5.0)
    assert res["admitted"] == 16 and not res["dup"]
    assert res["shards"] > 1, "16 keys should span shards"
    dup = door.admit_page(raw, "t-acme", timeout=5.0)
    assert dup["dup"] and dup["admitted"] == 0
    assert ks.tenant_state("t-acme") == {
        f"k{i}": f"v{i}" for i in range(16)}


# ---- end-to-end: HTTP tenant routing + shard-scoped anti-entropy ----

def test_http_tenant_routing_and_ks_pull():
    """The wire story in one test: X-CRDT-Tenant routes /data writes
    through the keyspace door, a quota shed surfaces as a tenant-labeled
    429, tenant reads come back un-qualified, and agent.ks_pull
    converges every shard onto the peer."""
    import threading
    import urllib.error
    import urllib.request

    from crdt_tpu.api.net import NodeHost, RemotePeer
    from crdt_tpu.keyspace import TENANT_HEADER

    cfg = ClusterConfig(keyspace_shards=2, keyspace_capacity=64,
                        keyspace_tenant_quota={"t-noisy": 2})
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[], config=cfg)
    threads = []
    for h in (a, b):
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()
        threads.append(t)
    try:
        def post(url, body, tenant=None):
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(), method="POST")
            if tenant is not None:
                req.add_header(TENANT_HEADER, tenant)
            return urllib.request.urlopen(req, timeout=5)

        assert post(a.url + "/data", {"k1": "v1", "k2": "v2"},
                    tenant="t-acme").status == 200
        # tenant-scoped read mirrors the write route, un-qualified
        req = urllib.request.Request(a.url + "/data")
        req.add_header(TENANT_HEADER, "t-acme")
        got = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert got == {"k1": "v1", "k2": "v2"}
        # the single plane never saw the tenant write
        assert a.node.get_state() == {}
        # quota-slice shed: tenant-labeled 429 with Retry-After
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(a.url + "/data", {f"k{i}": "v" for i in range(3)},
                 tenant="t-noisy")
        assert ei.value.code == 429
        shed = json.loads(ei.value.read())
        assert shed["tenant"] == "t-noisy" and shed["lane"] == TENANT_LANE
        assert float(ei.value.headers["Retry-After"]) > 0
        # /ks/data exposes per-shard occupancy and the tenant slice
        stats = json.loads(urllib.request.urlopen(
            a.url + "/ks/data", timeout=5).read())
        assert len(stats["shards"]) == 2
        view = json.loads(urllib.request.urlopen(
            a.url + "/ks/data?tenant=t-acme", timeout=5).read())
        assert view["state"] == {"k1": "v1", "k2": "v2"}
        # shard-scoped anti-entropy over real sockets
        assert b.agent.ks_pull(RemotePeer(a.url)) == 2
        assert b.keyspace.tenant_state("t-acme") == {"k1": "v1",
                                                     "k2": "v2"}
        for i in range(2):
            assert (b.keyspace.version_vector(i)
                    == a.keyspace.version_vector(i))
    finally:
        for h in (a, b):
            h._server.shutdown()
            h._server.server_close()


def test_config_keyspace_knobs_validated():
    ClusterConfig(keyspace_shards=2, keyspace_capacity=64,
                  keyspace_tenant_quota={"t-acme": 8})
    with pytest.raises(ValueError):
        ClusterConfig(keyspace_shards=-1)
    with pytest.raises(ValueError):
        ClusterConfig(keyspace_shards=2, keyspace_capacity=0)
    with pytest.raises(ValueError):
        ClusterConfig(keyspace_shards=2,
                      keyspace_tenant_quota={"bad:name": 8})
    with pytest.raises(ValueError):
        ClusterConfig(keyspace_shards=2,
                      keyspace_tenant_quota={"t-acme": 0})


# ---- online resharding: migration-plan properties ----

def test_reshard_migration_plan_properties():
    """Random S -> S' (grow AND shrink): the plan moves EXACTLY the
    owner-changed keys, never lists a key twice, and moved + kept
    covers the keyspace.  Minimal remap rides the HRW derivation:
    growing moves keys only TO the new shards, shrinking only FROM the
    departing ones — and the derived router is the same object the
    from-scratch construction would build."""
    import random

    from crdt_tpu.keyspace.reshard import (migration_plan, next_router,
                                           shard_members)

    rng = random.Random("reshard-plan-properties")
    tenants = ("t-acme", "t-bolt", "t-crab")
    qkeys = [qualify(tenants[i % len(tenants)], f"k{i:05d}")
             for i in range(400)]

    def owner(router, qkey):
        tenant, key = split_qualified(qkey)
        return router.owner_index(route_key(tenant, key))

    for _ in range(12):
        s = rng.randint(1, 9)
        sp = rng.choice([n for n in range(1, 10) if n != s])
        old = RendezvousRouter(shard_members(s))
        new = next_router(old, sp)
        # the minimal-remap chain ends at the from-scratch router
        assert list(new.members) == shard_members(sp)
        plan = migration_plan(old, new, qkeys)
        listed = [k for group in plan.values() for k in group]
        assert len(listed) == len(set(listed)), "a key moved twice"
        moved = set(listed)
        for (src, dst), group in plan.items():
            assert 0 <= src < s and 0 <= dst < sp and src != dst
            for qkey in group:
                assert owner(old, qkey) == src
                assert owner(new, qkey) == dst
        for qkey in qkeys:
            if qkey in moved:
                continue  # owner change checked above via its group
            # kept keys: same owner under both routers (coverage: every
            # key is either in exactly one moved group or kept in place)
            assert owner(old, qkey) == owner(new, qkey)
        if sp > s:  # grow: only keys the NEW members win may move
            assert all(dst >= s for (_, dst) in plan)
        else:  # shrink: only the departing members' keys may move
            assert all(src >= sp for (src, _) in plan)
        # HRW balance sanity at the endpoint: nothing pathological
        counts = collections.Counter(owner(new, k) for k in qkeys)
        assert len(counts) == min(sp, len(counts) or 1)
