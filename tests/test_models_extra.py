"""Semantics + law tests for the extra model families (MV-Register, EW/DW
flags, G-Set/2P-Set) — the lattices beyond the reference's counter store
that round out the framework (the reference resolves every concurrency
question by dropping one side, /root/reference/main.go:54-65; these keep
the deterministic-but-lossless alternatives available).

Law coverage for the core four lattices lives in test_lattice_laws.py; the
same four laws are asserted here for each new family on random *reachable*
states (built by random op sequences), and additionally on ARBITRARY random
arrays for the families whose join is total on any state (flags: pure max;
mvregister: lexicographic seq-then-max) — the sorted-array sets are
meaningful only on reachable (sorted, deduplicated) states.
"""
import zlib

import jax
import numpy as np
import pytest

from crdt_tpu.models import flags, gset, mvregister as mv
from tests.helpers import tree_equal

N_TRIALS = 20
W = 4


# ---- random reachable states ------------------------------------------------


def rand_mv(rng: np.random.Generator) -> mv.MVRegister:
    reg = mv.zero(W)
    for _ in range(rng.integers(0, 6)):
        reg = mv.write(
            reg, int(rng.integers(0, W)), int(rng.integers(0, 100)),
            int(rng.integers(0, 1000)),
        )
    return reg


def rand_ew(rng: np.random.Generator) -> flags.EWFlag:
    f = flags.ew_zero(W)
    for _ in range(rng.integers(0, 6)):
        w = int(rng.integers(0, W))
        f = flags.ew_enable(f, w) if rng.random() < 0.5 else flags.ew_disable(f, w)
    return f


def rand_dw(rng: np.random.Generator) -> flags.DWFlag:
    f = flags.dw_zero(W)
    for _ in range(rng.integers(0, 6)):
        w = int(rng.integers(0, W))
        f = flags.dw_enable(f, w) if rng.random() < 0.5 else flags.dw_disable(f, w)
    return f


def rand_gset(rng: np.random.Generator) -> gset.GSet:
    s = gset.g_empty(32)
    for _ in range(rng.integers(0, 8)):
        s = gset.g_add(s, int(rng.integers(0, 12)))
    return s


def rand_tpset(rng: np.random.Generator) -> gset.TwoPSet:
    s = gset.tp_empty(32)
    for _ in range(rng.integers(0, 8)):
        e = int(rng.integers(0, 12))
        s = gset.tp_add(s, e) if rng.random() < 0.7 else gset.tp_remove(s, e)
    return s


CASES = [
    ("mvregister", mv.join, rand_mv, lambda: mv.zero(W)),
    ("ewflag", flags.ew_join, rand_ew, lambda: flags.ew_zero(W)),
    ("dwflag", flags.dw_join, rand_dw, lambda: flags.dw_zero(W)),
    ("gset", gset.g_join, rand_gset, lambda: gset.g_empty(32)),
    ("tpset", gset.tp_join, rand_tpset, lambda: gset.tp_empty(32)),
]


@pytest.mark.parametrize("name,join,gen,zero", CASES,
                         ids=[c[0] for c in CASES])
def test_join_laws(name, join, gen, zero):
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    for _ in range(N_TRIALS):
        a, b, c = gen(rng), gen(rng), gen(rng)
        assert tree_equal(join(a, b), join(b, a)), "commutativity"
        assert tree_equal(join(join(a, b), c), join(a, join(b, c))), \
            "associativity"
        assert tree_equal(join(a, a), a), "idempotence"
        assert tree_equal(join(a, zero()), a), "identity"


def _arb_mv(rng: np.random.Generator) -> mv.MVRegister:
    return mv.MVRegister(
        seq=np.asarray(rng.integers(-1, 5, (W,)), np.int32),
        ts=np.asarray(rng.integers(0, 50, (W,)), np.int32),
        payload=np.asarray(rng.integers(0, 1000, (W,)), np.int32),
        obs=np.asarray(rng.integers(-1, 5, (W, W)), np.int32),
    )


def _arb_plane(rng: np.random.Generator) -> flags.TokenPlane:
    return flags.TokenPlane(
        tok=np.asarray(rng.integers(-1, 5, (W,)), np.int32),
        obs=np.asarray(rng.integers(-1, 5, (W, W)), np.int32),
    )


ARB_CASES = [
    ("mvregister_arb", mv.join, _arb_mv, lambda: mv.zero(W)),
    ("ewflag_arb", flags.ew_join,
     lambda rng: flags.EWFlag(plane=_arb_plane(rng)),
     lambda: flags.ew_zero(W)),
    ("dwflag_arb", flags.dw_join,
     lambda rng: flags.DWFlag(plane=_arb_plane(rng),
                              touched=bool(rng.random() < 0.5)),
     lambda: flags.dw_zero(W)),
]


@pytest.mark.parametrize("name,join,gen,zero", ARB_CASES,
                         ids=[c[0] for c in ARB_CASES])
def test_join_laws_arbitrary_states(name, join, gen, zero):
    """Joins that are total functions of ANY state (not just reachable ones)
    must satisfy the lattice laws unconditionally — this is what makes the
    mvregister tie-break (elementwise max on equal seqs) load-bearing."""
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    for _ in range(N_TRIALS):
        a, b, c = gen(rng), gen(rng), gen(rng)
        assert tree_equal(join(a, b), join(b, a)), "commutativity"
        assert tree_equal(join(join(a, b), c), join(a, join(b, c))), \
            "associativity"
        assert tree_equal(join(a, a), a), "idempotence"


# ---- MV-Register semantics --------------------------------------------------


def test_mv_concurrent_writes_both_visible():
    a = mv.write(mv.zero(W), 0, ts=10, payload=100)
    b = mv.write(mv.zero(W), 1, ts=11, payload=200)
    m = mv.join(a, b)
    vis, payload = mv.values(m)
    assert list(np.asarray(vis)) == [True, True, False, False]
    assert int(mv.n_siblings(m)) == 2
    assert {int(p) for p, v in zip(np.asarray(payload), np.asarray(vis)) if v} \
        == {100, 200}


def test_mv_covering_write_collapses_siblings():
    a = mv.write(mv.zero(W), 0, ts=10, payload=100)
    b = mv.write(mv.zero(W), 1, ts=11, payload=200)
    m = mv.join(a, b)            # {100, 200} are siblings
    m = mv.write(m, 2, ts=12, payload=300)  # observed both
    vis, payload = mv.values(m)
    assert int(mv.n_siblings(m)) == 1
    assert int(payload[np.asarray(vis).nonzero()[0][0]]) == 300


def test_mv_sequential_overwrite_same_writer():
    r = mv.write(mv.zero(W), 0, ts=1, payload=1)
    r = mv.write(r, 0, ts=2, payload=2)
    vis, payload = mv.values(r)
    assert int(mv.n_siblings(r)) == 1
    assert int(payload[0]) == 2 and bool(vis[0])


def test_mv_stale_writer_dominated_after_merge():
    a = mv.write(mv.zero(W), 0, ts=1, payload=1)
    b = mv.join(mv.zero(W), a)          # replica b observes a's write
    b = mv.write(b, 1, ts=2, payload=2)  # covers it
    m = mv.join(a, b)
    assert int(mv.n_siblings(m)) == 1
    vis, payload = mv.values(m)
    assert int(payload[1]) == 2 and bool(vis[1]) and not bool(vis[0])


def test_mv_batched_vmap():
    regs = mv.zero(W, batch=(8,))
    regs = jax.vmap(lambda r, p: mv.write(r, 0, 5, p))(
        regs, jax.numpy.arange(8, dtype=jax.numpy.int32)
    )
    out = jax.vmap(mv.join)(regs, regs)
    assert list(np.asarray(mv.n_siblings(out))) == [1] * 8


# ---- flag semantics ---------------------------------------------------------


def test_ew_concurrent_enable_wins():
    base = flags.ew_zero(W)
    ena = flags.ew_enable(base, 0)
    dis = flags.ew_disable(base, 1)  # concurrent: never saw the enable
    assert bool(flags.ew_value(flags.ew_join(ena, dis)))


def test_ew_observed_disable_wins_sequentially():
    f = flags.ew_enable(flags.ew_zero(W), 0)
    f = flags.ew_disable(f, 1)  # saw the enable
    assert not bool(flags.ew_value(f))
    f = flags.ew_enable(f, 0)   # re-enable with a fresh token
    assert bool(flags.ew_value(f))


def test_dw_concurrent_disable_wins():
    base = flags.dw_enable(flags.dw_zero(W), 0)
    ena = flags.dw_enable(base, 0)
    dis = flags.dw_disable(base, 1)  # concurrent with the re-enable
    assert not bool(flags.dw_value(flags.dw_join(ena, dis)))


def test_dw_initial_false_and_sequential_toggle():
    f = flags.dw_zero(W)
    assert not bool(flags.dw_value(f))
    f = flags.dw_enable(f, 0)
    assert bool(flags.dw_value(f))
    f = flags.dw_disable(f, 1)
    assert not bool(flags.dw_value(f))
    f = flags.dw_enable(f, 0)  # observed the disable: clears it
    assert bool(flags.dw_value(f))


def test_flag_swarm_pure_max_converge():
    """Flags are pure max-lattices: the swarm converge path works as-is."""
    from crdt_tpu.parallel import swarm

    r = 8
    state = flags.ew_zero(W, batch=(r,))
    state = flags.EWFlag(
        plane=state.plane.replace(
            tok=state.plane.tok.at[3, 0].set(0)  # replica 3 enables
        )
    )
    s = swarm.make(state)
    s = swarm.converge(
        s, jax.vmap(flags.ew_join), flags.ew_zero(W)
    )
    assert all(bool(v) for v in np.asarray(flags.ew_value(s.state)))


# ---- G-Set / 2P-Set semantics ----------------------------------------------


def test_gset_grow_only_union():
    a = gset.g_add(gset.g_add(gset.g_empty(16), 3), 7)
    b = gset.g_add(gset.g_add(gset.g_empty(16), 7), 9)
    u = gset.g_join(a, b)
    assert int(gset.g_size(u)) == 3
    for e in (3, 7, 9):
        assert bool(gset.g_contains(u, e))


def test_gset_duplicate_add_noop():
    s = gset.g_add(gset.g_add(gset.g_empty(8), 5), 5)
    assert int(gset.g_size(s)) == 1


def test_tpset_remove_wins_forever():
    s = gset.tp_add(gset.tp_empty(16), 1)
    s = gset.tp_remove(s, 1)
    assert not bool(gset.tp_contains(s, 1))
    s = gset.tp_add(s, 1)  # two-phase: re-add is a no-op
    assert not bool(gset.tp_contains(s, 1))


def test_tpset_concurrent_add_remove():
    a = gset.tp_add(gset.tp_empty(16), 1)
    b = gset.tp_remove(gset.tp_empty(16), 1)  # remove without observing
    m = gset.tp_join(a, b)
    assert not bool(gset.tp_contains(m, 1))  # remove-wins
    assert int(gset.tp_size(m)) == 0


def test_tpset_overflow_checked():
    a = gset.tp_empty(4)
    for e in range(4):
        a = gset.tp_add(a, e)
    b = gset.tp_add(gset.tp_empty(4), 99)
    _, n = gset.tp_join_checked(a, b)
    assert int(n) == 5  # true union exceeds capacity: detectable host-side

# ---- capacity growth migrations (round 2: grow/widen family) ----


def test_grow_preserves_state_and_joins():
    """grow() = tail padding on every table lattice: contents, order, and
    join results unchanged; shrink refused."""
    import jax.numpy as jnp

    from crdt_tpu.models import oplog, orset, rseq
    from tests.helpers import tree_equal

    s = orset.empty(8)
    for i in range(5):
        s = orset.add(s, i, 0, i)
    s = orset.remove(s, 2)
    g = orset.grow(s, 16)
    assert g.capacity == 16
    assert np.asarray(orset.member_mask(g, 8)).tolist() == \
        np.asarray(orset.member_mask(s, 8)).tolist()
    # joins at the grown capacity keep working (both sides migrated)
    j = orset.join(g, orset.grow(s, 16))
    assert tree_equal(j, g)
    with pytest.raises(ValueError, match="shrink"):
        orset.grow(s, 4)

    w = rseq.SeqWriter(rseq.empty(4), rid=0)
    for i in range(4):
        w.append(i)
    with pytest.raises(rseq.CapacityExceeded):
        w.append(9)
    w2 = rseq.SeqWriter(rseq.grow(w.state, 8), rid=0)  # the recovery path
    w2.append(9)
    assert w2.to_list() == [0, 1, 2, 3, 9]

    log = oplog.from_ops(4, {
        "ts": jnp.asarray([1, 2], jnp.int32),
        "rid": jnp.asarray([0, 0], jnp.int32),
        "seq": jnp.asarray([0, 1], jnp.int32),
        "key": jnp.asarray([0, 1], jnp.int32),
        "val": jnp.asarray([5, -3], jnp.int32),
        "payload": jnp.asarray([0, 0], jnp.int32),
        "is_num": jnp.asarray([True, True]),
    })
    big = oplog.grow(log, 16)
    assert int(oplog.size(big)) == 2
    kv_a = oplog.rebuild(log, 4)
    kv_b = oplog.rebuild(big, 4)
    np.testing.assert_array_equal(np.asarray(kv_a.num), np.asarray(kv_b.num))


def test_grow_columnar_requires_power_of_two():
    from crdt_tpu.models import oplog_columnar as oc

    col = oc.empty(8, 4)
    g = oc.grow(col, 16)
    assert g.capacity == 16 and g.lanes == 4
    with pytest.raises(ValueError, match="power of two"):
        oc.grow(col, 12)
    with pytest.raises(ValueError, match="shrink"):
        oc.grow(col, 4)
