"""Engine auto-selection (crdt_tpu.models.oplog_engine): the columnar fused
kernel must be the DEFAULT swarm engine, the generic path the loud
exception — and the two engines must be observationally identical on
randomized swarms (round-2 verdict item 2's done-criterion)."""
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crdt_tpu.models import oplog, oplog_engine as eng
from tests.test_oplog_columnar import (
    _assert_logs_equal,
    _op_pool,
    _random_batch,
)


def _swarm(seed, r=8, c=32, n=40):
    rng = np.random.default_rng(seed)
    return _random_batch(rng, r, c, _op_pool(rng, n))


def test_columnar_is_the_default_engine():
    sw = eng.plan(_swarm(0))
    assert sw.engine == "columnar"
    assert sw.fallback_reason is None
    # and it STAYS columnar across rounds (resident state, no re-stack)
    assert sw.converge().engine == "columnar"


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_engines_agree_on_randomized_swarms(seed):
    """The A/B criterion: converge / gossip / rebuild identical across
    engines, including the overflow count."""
    state = _swarm(seed)
    fast = eng.plan(state)
    slow = eng.plan(state, force_generic=True)
    assert fast.engine == "columnar" and slow.engine == "generic"

    fc, fnu = fast.converge_checked()
    sc, snu = slow.converge_checked()
    _assert_logs_equal(fc.rows(), sc.rows())
    assert int(fnu) == int(snu)

    r = state.ts.shape[0]
    peers = jnp.asarray((np.arange(r) + 3) % r, jnp.int32)
    _assert_logs_equal(
        fast.gossip_round(peers).rows(), slow.gossip_round(peers).rows()
    )

    for f_leaf, s_leaf in zip(
        jax.tree.leaves(fc.rebuild(16)), jax.tree.leaves(sc.rebuild(16))
    ):
        np.testing.assert_array_equal(np.asarray(f_leaf), np.asarray(s_leaf))


def test_engines_agree_with_dead_replicas():
    state = _swarm(4)
    alive = jnp.asarray([True, False, True, True, False, True, True, True])
    fast = eng.plan(state, alive=alive)
    slow = eng.plan(state, alive=alive, force_generic=True)
    fc, _ = fast.converge_checked()
    sc, _ = slow.converge_checked()
    _assert_logs_equal(fc.rows(), sc.rows())
    # dead replicas keep their stale rows on both engines
    for i in (1, 4):
        for f in ("ts", "rid", "seq", "key"):
            np.testing.assert_array_equal(
                np.asarray(getattr(fc.rows(), f)[i]),
                np.asarray(getattr(state, f)[i]),
            )


def test_fallback_is_loud_and_correct_nonpow2_capacity():
    state = _swarm(5, c=24)  # 24 is not a power of two
    with pytest.warns(eng.EngineFallback, match="power of two"):
        sw = eng.plan(state)
    assert sw.engine == "generic"
    assert "power of two" in sw.fallback_reason
    # correctness is engine-independent: generic result == the plain
    # swarm.converge ground truth
    from crdt_tpu.ops import joins
    from crdt_tpu.parallel import swarm as swarm_mod

    want = swarm_mod.converge(
        swarm_mod.make(state), jax.vmap(oplog.merge), oplog.empty(24)
    ).state
    _assert_logs_equal(sw.converge().rows(), want)


def test_fallback_on_foreign_negative_rid():
    """Go-format ops (rid = -1, crdt_tpu.api.node) cannot bit-pack; the
    engine must fall back, not corrupt the sort order."""
    rng = np.random.default_rng(6)
    pool = _op_pool(rng, 24)
    pool["rid"][:4] = -1
    state = _random_batch(rng, 4, 32, pool)
    with pytest.warns(eng.EngineFallback, match="negative identity"):
        sw = eng.plan(state)
    assert sw.engine == "generic"


def test_fallback_on_pack_budget_overflow():
    rng = np.random.default_rng(7)
    pool = _op_pool(rng, 24)
    pool["seq"] = pool["seq"].astype(np.int64) * 0 + (1 << 24)
    pool["seq"] = pool["seq"].astype(np.int32)
    pool["rid"][:] = 200  # 8 rid bits
    pool["key"][:] = 120  # 7 key bits; 8 + 25 + 7 > 31
    state = _random_batch(rng, 4, 32, pool)
    with pytest.warns(eng.EngineFallback, match="pack budget"):
        sw = eng.plan(state)
    assert sw.engine == "generic"


def test_pinned_bits_skip_the_probe():
    """Callers that know their layout pin bits and never pay the host-side
    range scan (and never warn)."""
    state = _swarm(8)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sw = eng.plan(state, bits=(4, 22, 5))
    assert sw.engine == "columnar"
    slow = eng.plan(state, force_generic=True)
    _assert_logs_equal(sw.converge().rows(), slow.converge().rows())


def test_set_alive_round_trip():
    sw = eng.plan(_swarm(9))
    sw = sw.set_alive(2, False)
    assert not bool(sw.alive[2])
    assert sw.engine == "columnar"
