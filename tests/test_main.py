"""Smoke tests for the runnable entry point (python -m crdt_tpu): the
reference's end-to-end deployment experience (main.go:316-327) must boot,
serve, converge, and exit cleanly in both modes."""
import subprocess
import sys


def _run(args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "crdt_tpu", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_demo_mode_converges():
    p = _run([
        "--replicas", "3", "--ephemeral-ports", "--duration", "4",
        "--gossip-ms", "40", "--write-ms", "25", "--report-every", "1",
        "--seed", "3", "--dump-state",
    ])
    assert p.returncode == 0, p.stdout + p.stderr
    assert "serving 3 replicas" in p.stdout
    assert "converged=True" in p.stdout


def test_daemon_mode_boots_and_exits():
    p = _run([
        "--daemon", "--rid", "7", "--port", "0", "--duration", "1",
    ])
    assert p.returncode == 0, p.stdout + p.stderr
    assert "replica rid=7 (base 7, incarnation 0, restored=False) serving on" in p.stdout
    assert "final: state_keys=0" in p.stdout


def test_demo_mode_all_lattice_surfaces(request):
    """--with-sets + --with-seqs: the reference-style demo drives all
    three lattice surfaces (KV + OR-Set + sequence) with scheduled GC
    barriers and converges every one of them (round-4: the flagship
    extensions visible in the demo, not only in soaks)."""
    p = _run([
        "--replicas", "3", "--ephemeral-ports", "--duration", "8",
        "--gossip-ms", "60", "--write-ms", "30", "--report-every", "2",
        "--seed", "5", "--with-sets", "--with-seqs",
        "--set-collect-every", "4", "--seq-collect-every", "5",
    ], timeout=240)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "set_converged=True" in p.stdout.splitlines()[-1]
    assert "seq_converged=True" in p.stdout.splitlines()[-1]
    assert "converged=True" in p.stdout.splitlines()[-1]
