"""CI wrapper for the Jepsen-lite soak (crdt_tpu.harness.soak): short
randomized schedules across seeds and configurations.  The invariants (I1
durability, I2 availability, I3 liveness, I4 schedule safety) are asserted
inside the runner; these tests choose adversarial configurations."""
import pytest

from crdt_tpu.harness.soak import SoakRunner
from crdt_tpu.utils.config import ClusterConfig


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_basic(seed):
    r = SoakRunner(seed=seed).run(300)
    assert r.writes_accepted > 0
    assert r.final_state  # something survived to the fixpoint


def test_soak_with_scheduled_compaction():
    """Barriers racing faults: tick-SCHEDULED compaction (compact_every)
    plus explicit random barriers, while nodes die and revive — the
    frontier chain rule must keep every schedule legal."""
    cfg = ClusterConfig(n_replicas=5, compact_every=2)
    r = SoakRunner(cfg, seed=7, p_compact=0.1).run(400)
    assert r.barriers + r.barriers_skipped > 0
    assert r.barriers > 0  # at least one barrier actually folded mid-run
    assert r.final_state


def test_soak_full_gossip_mode():
    cfg = ClusterConfig(n_replicas=4, delta_gossip=False)
    r = SoakRunner(cfg, seed=3).run(250)
    assert r.final_state


def test_soak_aggressive_faults():
    """Kill-heavy schedule: up to n-1 dead at once, many revivals."""
    r = SoakRunner(
        seed=11, p_write=0.3, p_gossip=0.3, p_kill=0.2, p_revive=0.15,
        p_compact=0.05,
    ).run(400)
    assert r.kills >= 5 and r.revivals >= 5
    assert r.writes_rejected_dead > 0  # I2 actually exercised
    assert r.final_state


def test_soak_reference_topology():
    """The reference's own friend list (self + dead ports, quirk §0.1.9)."""
    cfg = ClusterConfig(n_replicas=5, reference_topology=True)
    r = SoakRunner(cfg, seed=5).run(300)
    assert r.final_state


@pytest.mark.parametrize("seed", [0, 1])
def test_network_soak(seed):
    """The soak over real sockets: HTTP writes, delta gossip, alive-toggle
    faults, coordinator barriers — same four invariants."""
    from crdt_tpu.harness.soak import NetworkSoakRunner

    r = NetworkSoakRunner(n=3, seed=seed).run(250)
    assert r.writes_accepted > 0
    assert r.final_state
    assert r.barriers + r.barriers_skipped > 0
