"""Black-box parity against the reference's OBSERVABLE behavior, over real
HTTP: the quirks-ON oracle server (crdt_tpu.oracle.shim) must reproduce
the Go server's responses bug-for-bug, and the fixed framework surface
must differ exactly where the fixes are documented (SURVEY.md §0.1)."""
from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from crdt_tpu.oracle.shim import OracleHttpCluster
from crdt_tpu.utils.clock import ManualClock


def _req(url, method="GET", data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=5) as res:
            return res.status, res.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture
def quirky():
    c = OracleHttpCluster(n=2, clock=ManualClock(start=1_000_000))
    c.start()
    yield c
    c.stop()


def _tick(c):
    c.nodes[0].clock.advance(10)


def test_surface_matches_reference(quirky):
    u = quirky.urls[0]
    assert _req(u + "/ping") == (200, b"Pong")
    assert _req(u + "/data")[1] == b"{}"
    # the broken /condition route: ALWAYS 500, exactly like the Go server
    # whose route lacks the :alive_status binding (quirk 0.1.7)
    assert _req(u + "/condition")[0] == 500
    assert _req(u + "/condition?alive_status=false")[0] == 500
    assert _req(u + "/nope")[0] == 404
    # invalid body: 500 written WITHOUT return, nil command logged, then
    # "Inserted" appended to the same response (main.go:183-187, 208)
    code, body = _req(u + "/data", "POST", b"not json")
    assert (code, body) == (500, b"Request body is invalidInserted")
    code, body = _req(u + "/data", "POST", json.dumps({"x": "5"}).encode())
    assert (code, body) == (200, b"Inserted")  # main.go:208


def test_multikey_early_return_over_http(quirky):
    """Quirk 0.1.4: a multi-key command stops applying to CurrentState at
    the first previously-unseen key; the LOG still holds every key, so a
    merge-time rebuild surfaces them all."""
    u = quirky.urls[0]
    _req(u + "/data", "POST", json.dumps({"a": "1", "b": "2"}).encode())
    state = json.loads(_req(u + "/data")[1])
    assert state == {"a": "1"}  # b vanished from the eager fold
    # but the wire carries the whole command
    wire = json.loads(_req(u + "/gossip")[1])
    assert list(wire.values()) == [{"a": "1", "b": "2"}]
    # peer adopts it (own newer entry first — tail-drop, 0.1.3) and the
    # merge-time rebuild surfaces BOTH keys (its own entry is excluded,
    # 0.1.1)
    _tick(quirky)
    _req(quirky.urls[1] + "/data", "POST", json.dumps({"z": "9"}).encode())
    _tick(quirky)
    assert quirky.gossip_once(1, 0)
    assert json.loads(_req(quirky.urls[1] + "/data")[1]) == {"a": "1", "b": "2"}


def test_tail_drop_empty_replica_adopts_nothing(quirky):
    """Quirk 0.1.3 at its extreme: the two-pointer union stops at the
    shorter log, so a replica with an EMPTY log adopts zero entries from a
    pull — faithful to main.go:49 (self-healing only because replicas keep
    writing and gossip repeats)."""
    u0, u1 = quirky.urls
    _req(u0 + "/data", "POST", json.dumps({"x": "5"}).encode())
    _tick(quirky)
    assert quirky.gossip_once(1, 0)
    assert json.loads(_req(u1 + "/data")[1]) == {}  # nothing adopted!


def test_local_op_exclusion_over_http(quirky):
    """Quirk 0.1.1: after its first merge, a replica's OWN writes no longer
    count toward its local state (the failed type assertion), while peers
    keep counting them — plus the tail-drop (0.1.3) hiding the remote's
    newest entry."""
    u0, u1 = quirky.urls
    _req(u0 + "/data", "POST", json.dumps({"x": "5"}).encode())  # t1 @ node0
    _tick(quirky)
    _req(u1 + "/data", "POST", json.dumps({"z": "9"}).encode())  # t2 @ node1
    _tick(quirky)
    assert quirky.gossip_once(1, 0)  # node1 adopts t1 (older than its t2)
    # node1's rebuild: its OWN t2 is excluded (pointer entry), adopted t1
    # counts — so x survives and node1's own z vanishes locally
    assert json.loads(_req(u1 + "/data")[1]) == {"x": "5"}
    assert json.loads(_req(u0 + "/data")[1]) == {"x": "5"}  # pre-merge: eager
    assert quirky.gossip_once(0, 1)
    # node0's merge: equal-t1 keys -> local pointer retained; t2 is beyond
    # node0's newest local entry -> tail-dropped; rebuild excludes its own
    # t1 -> node0 reads EMPTY while node1 still reads x=5
    assert json.loads(_req(u0 + "/data")[1]) == {}
    assert json.loads(_req(u1 + "/data")[1]) == {"x": "5"}
    # the fixed framework keeps counting everything (the documented fix)
    from crdt_tpu.api.net import NodeHost, RemotePeer

    a = NodeHost(rid=0, peers=[])
    b = NodeHost(rid=1, peers=[])
    import threading

    for h in (a, b):
        threading.Thread(target=h._server.serve_forever, daemon=True).start()
    try:
        a.agent.peers = [RemotePeer(b.url)]
        b.agent.peers = [RemotePeer(a.url)]
        RemotePeer(a.url).add_command({"x": "5"})
        b.agent.gossip_once()
        a.agent.gossip_once()
        assert RemotePeer(a.url).get_state() == {"x": "5"}  # fix holds
    finally:
        for h in (a, b):
            h._server.shutdown()
            h._server.server_close()


def test_same_ms_overwrite_over_http(quirky):
    """Quirk 0.1.2: the log key is the bare millisecond; a second write in
    the same ms replaces the first in the log."""
    u = quirky.urls[0]
    _req(u + "/data", "POST", json.dumps({"x": "1"}).encode())
    _req(u + "/data", "POST", json.dumps({"y": "2"}).encode())  # same ms
    wire = json.loads(_req(u + "/gossip")[1])
    assert len(wire) == 1 and list(wire.values()) == [{"y": "2"}]


def test_numeric_convergence_where_no_quirk_fires(quirky):
    """Distinct-ms single-writer traffic adopted by a peer converges to the
    same sums the fixed framework produces — the capability under the
    bugs is intact, which is what 'parity' means here."""
    u0, u1 = quirky.urls
    for delta in ("-11", "-20", "5"):
        _req(u0 + "/data", "POST", json.dumps({"k": delta}).encode())
        _tick(quirky)
    # node1 needs a NEWER local entry for the two-pointer walk to adopt
    # the remote ops (quirk 0.1.3); its own entry is then excluded from
    # its rebuild (quirk 0.1.1), leaving exactly the adopted sum
    _req(u1 + "/data", "POST", json.dumps({"z": "1"}).encode())
    _tick(quirky)
    assert quirky.gossip_once(1, 0)
    assert json.loads(_req(u1 + "/data")[1]) == {"k": "-26"}
