"""crdtprove self-tests: the bit-blaster refutes planted defective joins
with exact counterexamples, the committed verdict ledger covers every
registered join, the fingerprint cache skips unchanged joins (pinned via
the blast call counter), and the witnessed-race detector catches a
planted unsynchronized access while staying silent on properly
synchronized code.
"""
import threading

import jax.numpy as jnp
import pytest

from crdt_tpu.analysis.verify import ledger, prove, race
from crdt_tpu.analysis.verify.domains import build_domain
from crdt_tpu.ops.joins import JoinSpec, registered_joins


# ------------------------------------------------- planted defective joins


def _avg_spec():
    """Weighted mean masquerading as a join: floats AND asymmetric.
    Refuted on commutativity (0.6a+0.4b != 0.6b+0.4a whenever a != b)."""
    def avg_join(a, b):
        return 0.6 * a + 0.4 * b

    neutral = lambda: jnp.zeros((2,), jnp.float32)  # noqa: E731
    small = lambda: [jnp.asarray([v, 0.0], jnp.float32)  # noqa: E731
                     for v in (0.0, 1.0, 2.0)]
    return JoinSpec("bad_avg", avg_join, lambda: (neutral(), neutral()),
                    neutral=neutral, small=small)


def _sat_spec():
    """Saturating int8 add: the unsaturated a+b wraps at 127 long before
    the clamp at 100 can catch it (80+80 -> -96), so idempotence and
    inflationarity both break with concrete witnesses."""
    def sat_join(a, b):
        return jnp.minimum(a + b, jnp.int8(100))

    neutral = lambda: jnp.zeros((2,), jnp.int8)  # noqa: E731
    small = lambda: [jnp.asarray([v, 0], jnp.int8)  # noqa: E731
                     for v in (0, 3, 80)]
    return JoinSpec("bad_sat", sat_join, lambda: (neutral(), neutral()),
                    neutral=neutral, small=small)


def test_prover_refutes_noncommutative_float_join():
    entry = prove.prove_spec(_avg_spec(), registry={})
    assert entry["verdict"] == "refuted"
    assert "commutative" in entry["refuted_laws"]
    ce = entry["laws"]["commutative"]["counterexample"]
    # the counterexample is concrete: both operand states and both sides
    # of the violated equation, leaf-wise
    assert set(ce) == {"a", "b", "lhs", "rhs"}
    assert ce["lhs"] != ce["rhs"]


def test_prover_refutes_saturating_overflow_join():
    entry = prove.prove_spec(_sat_spec(), registry={})
    assert entry["verdict"] == "refuted"
    assert "idempotent" in entry["refuted_laws"]
    assert "inflationary" in entry["refuted_laws"]
    ce = entry["laws"]["idempotent"]["counterexample"]
    # join(a, a) wrapped: the lhs is NOT the state itself
    assert ce["lhs"] != ce["rhs"]


def test_planted_joins_trip_the_hazard_pass():
    """The semantic jaxpr layer flags the same two planted joins
    statically: float accumulation (CRDT105) and narrow-int wrap
    (CRDT107) — defense in depth ahead of any bit-blasting."""
    import jax

    from crdt_tpu.analysis.verify import hazards

    spec = _avg_spec()
    closed = jax.make_jaxpr(spec.join)(*spec.example())
    rules = {f.rule for f in hazards.check_join_hazards(
        "bad_avg", spec, closed.jaxpr, "fixture.py", 1)}
    assert "CRDT105" in rules

    spec = _sat_spec()
    closed = jax.make_jaxpr(spec.join)(*spec.example())
    rules = {f.rule for f in hazards.check_join_hazards(
        "bad_sat", spec, closed.jaxpr, "fixture.py", 1)}
    assert "CRDT107" in rules


def test_real_joins_all_prove():
    """Spot-check the blaster end-to-end on two real lattices (the full
    registry sweep lives in the committed ledger, gated by
    test_committed_ledger_covers_registry)."""
    registry = registered_joins()
    for name in ("gcounter", "lww"):
        entry = prove.prove_spec(registry[name], registry)
        assert entry["verdict"] == "proved", (name, entry)
        assert entry["domain"]["closed"]
        for law, res in entry["laws"].items():
            assert res["holds"], (name, law)


# -------------------------------------------------------- verdict ledger


def test_committed_ledger_covers_registry():
    """The acceptance invariant behind `verify --check-ledger`: every
    registered join has a matching, non-refuted verdict in the committed
    analysis/verdicts.json — and in this tree, every one is proved."""
    led = ledger.load()
    assert led is not None, "analysis/verdicts.json missing"
    problems, _stale = ledger.check(led)
    assert problems == []
    entries = led["joins"]
    registry = registered_joins()
    assert set(registry) <= set(entries)
    for name in registry:
        e = entries[name]
        assert e["verdict"] in ("proved", "assumed"), (name, e["verdict"])
        if e["verdict"] == "assumed":
            assert e.get("reason"), f"{name}: assumed without a reason"
        else:
            assert e["domain"]["closed"], name


def test_verified_joins_reflects_ledger():
    """ops.joins.verified_joins() is the consumer surface: proved +
    fingerprint-fresh entries mark the spec verified."""
    from crdt_tpu.ops.joins import verified_joins

    verified = verified_joins()
    assert set(verified) == set(registered_joins())
    assert all(s.verified for s in verified.values())


def _tiny_registry():
    def jmax(a, b):
        return jnp.maximum(a, b)

    def jor(a, b):
        return jnp.logical_or(a, b)

    zi = lambda: jnp.zeros((2,), jnp.int32)  # noqa: E731
    zb = lambda: jnp.zeros((2,), bool)  # noqa: E731
    return {
        "tmax": JoinSpec("tmax", jmax, lambda: (zi(), zi()), neutral=zi,
                         small=lambda: [jnp.asarray([v, 0], jnp.int32)
                                        for v in (1, 2)]),
        "tor": JoinSpec("tor", jor, lambda: (zb(), zb()), neutral=zb,
                        small=lambda: [jnp.asarray([True, False])]),
    }


def test_ledger_cache_skips_unchanged_joins():
    reg = _tiny_registry()
    led, recomputed = ledger.compute(registry=reg)
    assert sorted(recomputed) == ["tmax", "tor"]
    assert all(e["verdict"] == "proved" for e in led["joins"].values())

    # unchanged fingerprints: a cached recompute blasts NOTHING
    before = prove.blast_call_count()
    led2, recomputed = ledger.compute(cached=led, registry=reg)
    assert recomputed == []
    assert prove.blast_call_count() == before
    assert led2["joins"] == led["joins"]

    # a drifted fingerprint invalidates exactly that join
    led["joins"]["tmax"]["fingerprint"] = "0" * 16
    _led3, recomputed = ledger.compute(cached=led, registry=reg)
    assert recomputed == ["tmax"]
    assert prove.blast_call_count() == before + 1


def test_fingerprint_tracks_join_body():
    zi = lambda: jnp.zeros((2,), jnp.int32)  # noqa: E731
    a = JoinSpec("t", lambda a, b: jnp.maximum(a, b),
                 lambda: (zi(), zi()), neutral=zi)
    b = JoinSpec("t", lambda a, b: jnp.minimum(a, b),
                 lambda: (zi(), zi()), neutral=zi)
    c = JoinSpec("t", lambda a, b: jnp.maximum(a, b),
                 lambda: (zi(), zi()), neutral=zi)
    assert prove.join_fingerprint(a) != prove.join_fingerprint(b)
    assert prove.join_fingerprint(a) == prove.join_fingerprint(c)


def test_composite_verdict_downgrades_with_weak_part():
    """A composite's `proved` is conditional on its parts: the ledger
    downgrade pass turns it `assumed` when a part is not proved."""
    entries = {
        "leaf": {"verdict": "assumed", "parts": [],
                 "reason": "domain capped"},
        "comp": {"verdict": "proved", "parts": ["leaf"]},
    }
    ledger._downgrade_composites(entries)
    assert entries["comp"]["verdict"] == "assumed"
    assert "leaf" in entries["comp"]["reason"]


def test_domain_closure_is_exhaustive():
    """The soundness backbone: a closed domain really is join-closed, so
    a quantifier over it is a theorem about the sub-semilattice."""
    from crdt_tpu.analysis.verify.domains import state_key

    reg = registered_joins()
    dom = build_domain(reg["gcounter"])
    assert dom.closed
    keys = {state_key(s) for s in dom.states}
    for a in dom.states:
        for b in dom.states:
            assert state_key(reg["gcounter"].join(a, b)) in keys


# ------------------------------------------------------ verify CLI matrix


def test_verify_cli_exit_codes(tmp_path, monkeypatch):
    from crdt_tpu.analysis import __main__ as cli
    from crdt_tpu.ops import joins as joins_mod

    reg = _tiny_registry()
    monkeypatch.setattr(joins_mod, "registered_joins", lambda: reg)
    lp = tmp_path / "verdicts.json"

    # no ledger yet: the gate is red, a recompute is green
    assert cli.main(["verify", "--check-ledger", "--ledger", str(lp)]) == 1
    assert cli.main(["verify", "--write-ledger", "--ledger", str(lp)]) == 0
    assert lp.exists()
    assert cli.main(["verify", "--check-ledger", "--ledger", str(lp)]) == 0

    # a refuted join fails both the recompute and the gate, and the
    # SARIF export carries the CRDT301 result
    reg["bad_sat"] = _sat_spec()
    sarif_path = tmp_path / "out.sarif"
    assert cli.main(["verify", "--write-ledger", "--ledger", str(lp)]) == 1
    assert cli.main(["verify", "--check-ledger", "--ledger", str(lp),
                     "--sarif", str(sarif_path)]) == 1
    import json

    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert any(r["ruleId"] == "CRDT301" for r in results)

    # dropping the bad join leaves a stale entry, which must NOT fail
    del reg["bad_sat"]
    assert cli.main(["verify", "--check-ledger", "--ledger", str(lp)]) == 0

    # body drift (same name, different computation) re-reddens the gate
    reg["tmax"] = JoinSpec(
        "tmax", lambda a, b: jnp.maximum(a, b) + 1,
        reg["tmax"].example, neutral=reg["tmax"].neutral)
    assert cli.main(["verify", "--check-ledger", "--ledger", str(lp)]) == 1


# -------------------------------------------------- witnessed-race checker


class _Box:
    def __init__(self):
        self.val = 0
        self.items = []


def _hammer(box, n=200):
    for _ in range(n):
        box.val += 1
        box.items.append(1)


def test_race_detector_catches_planted_race():
    assert race.install(watch=[(_Box, "val"), (_Box, "items")]) > 0
    try:
        box = _Box()
        ts = [threading.Thread(target=_hammer, args=(box,))
              for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        ws = race.witnesses()
        assert ws, "two unsynchronized writers produced no witness"
        w = ws[0]
        assert w.cls == "_Box"
        assert w.attr in ("val", "items")
        # the witness is actionable: both stacks point at the fixture
        assert any("_hammer" in line for line in w.prior_stack)
        assert any("_hammer" in line for line in w.current_stack)
        counts = race.access_counts()
        assert counts["_Box.val"]["writes"] >= 2
    finally:
        race.uninstall()
    # uninstalled objects keep working (stale traced wrappers are inert)
    box2 = _Box()
    box2.val = 5
    box2.items.append(1)
    assert (box2.val, box2.items) == (5, [1])


def test_race_detector_accepts_lock_discipline():
    assert race.install(watch=[(_Box, "val"), (_Box, "items")]) > 0
    try:
        box = _Box()  # built AFTER install: its list is traced
        lock = threading.Lock()  # likewise: a traced lock

        def worker():
            for _ in range(200):
                with lock:
                    box.val += 1
                    box.items.append(1)

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert race.witnesses() == []
        assert box.val == 400
        # and the instrumentation was demonstrably live
        assert race.access_counts()["_Box.val"]["writes"] >= 400
    finally:
        race.uninstall()


def test_race_detector_accepts_fork_join_ordering():
    """start/join edges alone (no lock) are a valid happens-before
    chain: parent -> child via start, child -> parent via join."""
    assert race.install(watch=[(_Box, "val")]) > 0
    try:
        box = _Box()
        box.val = 1
        t = threading.Thread(target=lambda: setattr(box, "val", 2))
        t.start()
        t.join()
        box.val = 3
        assert race.witnesses() == []
    finally:
        race.uninstall()


def test_race_detector_accepts_event_handoff():
    """Event.set/wait is an acquire/release pair: a value published
    before set() is safely read after wait()."""
    assert race.install(watch=[(_Box, "val")]) > 0
    try:
        box = _Box()
        ev = threading.Event()

        def producer():
            box.val = 42
            ev.set()

        got = []

        def consumer():
            ev.wait(5.0)
            got.append(box.val)

        tp = threading.Thread(target=producer)
        tc = threading.Thread(target=consumer)
        tc.start()
        tp.start()
        tp.join()
        tc.join()
        assert got == [42]
        assert race.witnesses() == []
    finally:
        race.uninstall()


def test_race_detector_runtime_watchpoints_resolve():
    """DEFAULT_WATCH must resolve against the live runtime modules — a
    renamed attr would silently un-instrument the soak."""
    points = race._resolve_default_watch()
    assert len(points) >= 7
    for cls, attr in points:
        probe = cls.__new__(cls)
        # the attr is either a slot or assigned in __init__; both
        # materialize on a constructed instance, which we can't always
        # build here — so just require the name to be plausible: a slot,
        # a class attr, or mentioned in __init__
        import inspect

        src = inspect.getsource(cls.__init__)
        slots = getattr(cls, "__slots__", ())
        assert (attr in slots or hasattr(cls, attr)
                or f"self.{attr}" in src), (cls, attr)


@pytest.mark.slow
def test_race_detector_clean_on_threaded_runtime():
    """The CI contract in miniature: a real (small) nemesis soak under
    the detector reports zero witnesses with live instrumentation."""
    from crdt_tpu.harness import nemesis_soak

    installed = race.install()
    assert installed > 0
    try:
        nemesis_soak.run_soak(seed=3, nodes=2, steps=40)
        rpt = race.report()
        assert rpt["witness_count"] == 0, "\n".join(rpt["witnesses"])
        traffic = sum(c["reads"] + c["writes"]
                      for c in rpt["access_counts"].values())
        assert traffic > 0, "watchpoints saw no traffic"
    finally:
        race.uninstall()
