"""Golden fixtures for the quirk-compat shim, derived from a line-by-line
read of /root/reference/main.go — NOT from the oracle the shim wraps.

Round-1 verdict: black-box parity ran only against crdt_tpu.oracle.shim,
whose fidelity was asserted by the same codebase being tested; a misread of
a Go behavior would be invisible (oracle and shim agreeing with each other).
These fixtures pin the shim to the *source*: every expected byte cites the
main.go line that produces it, so a fidelity bug must now contradict a
literal reading of the reference.

Go serialization facts encoded here (all checkable against the stdlib docs
plus the cited lines — no Go toolchain in this image):

* gin ``c.String`` writes ``text/plain; charset=utf-8`` and the exact
  format string; ``err.Error()`` for strconv failures renders as
  ``strconv.<Fn>: parsing "<in>": invalid syntax`` (strconv.NumError).
* gin ``c.JSON`` (GetState, main.go:132) uses encoding/json WITH HTML
  escaping: map keys sorted lexicographically, no whitespace, and
  ``<``/``>``/``&`` escaped as ``\\u003c``/``\\u003e``/``\\u0026``.
* ``Diff.ToJSON()`` (Gossip, main.go:159) goes through gods' treemap
  ToJSON, which builds a ``map[string]interface{}`` and json.Marshals it —
  so gossip keys are ordered as STRINGS, not numbers (fixture below pins
  the "1000" < "999" case), and a nil ``*Command`` (the invalid-body Put,
  main.go:187) marshals as ``null``.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from crdt_tpu.oracle.shim import OracleHttpCluster
from crdt_tpu.utils.clock import ManualClock


def _req(url, method="GET", data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=5) as res:
            return res.status, res.read(), res.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type")


@pytest.fixture
def shim():
    c = OracleHttpCluster(n=1, clock=ManualClock(start=1_000_000))
    c.start()
    yield c
    c.stop()


TEXT = "text/plain; charset=utf-8"

# (name, setup writes [(ms-advance, body-bytes)], request (method, path,
#  body), want (status, body, content-type), main.go citation)
FIXTURES = [
    (
        "ping_alive",
        [],
        ("GET", "/ping", None),
        (200, b"Pong", TEXT),
        "main.go:120 c.String(200, \"Pong\")",
    ),
    (
        "get_state_empty",
        [],
        ("GET", "/data", None),
        (200, b"{}", "application/json; charset=utf-8"),
        "main.go:132 c.JSON(200, CurrentState) on the empty initial state "
        "(main.go:218)",
    ),
    (
        "condition_no_param",
        [],
        ("GET", "/condition", None),
        (500, b'strconv.ParseBool: parsing "": invalid syntax', TEXT),
        "main.go:266 registers /condition WITHOUT :alive_status, so "
        "c.Param() is \"\" and ParseBool errors (main.go:145-148)",
    ),
    (
        "condition_query_param_still_broken",
        [],
        ("GET", "/condition?alive_status=false", None),
        (500, b'strconv.ParseBool: parsing "": invalid syntax', TEXT),
        "main.go:145 reads a PATH param; query strings never bind it",
    ),
    (
        "unknown_route_404",
        [],
        ("GET", "/nope", None),
        (404, b"404 page not found", TEXT),
        "gin's default NoRoute body (no custom handler registered, "
        "main.go:262-266)",
    ),
    (
        "post_new_key_early_return",
        [],
        ("POST", "/data", b'{"x":"5"}'),
        (200, b"Inserted", TEXT),
        "main.go:191-193: unseen key -> set verbatim, 200 \"Inserted\"",
    ),
    (
        "post_invalid_body_double_write",
        [],
        ("POST", "/data", b"not json"),
        (500, b"Request body is invalidInserted", TEXT),
        "main.go:184-186 writes the 500 WITHOUT return (quirk 0.1.11); "
        "main.go:187 still Puts the nil command; the nil-map range loop "
        "(main.go:188) is a no-op; main.go:208 appends \"Inserted\" to the "
        "already-written response",
    ),
    (
        "post_current_value_not_numeric",
        [(0, b'{"k":"abc"}')],
        ("POST", "/data", b'{"k":"5"}'),
        (500, b'strconv.Atoi: parsing "abc": invalid syntax', TEXT),
        "main.go:195-198: Atoi(CurrentState[k]) fails -> "
        "c.String(500, err.Error())",
    ),
    (
        "post_delta_not_numeric",
        [(0, b'{"n":"5"}')],
        ("POST", "/data", b'{"n":"x"}'),
        (500, b'strconv.Atoi: parsing "x": invalid syntax', TEXT),
        "main.go:200-203: Atoi(value) fails -> c.String(500, err.Error())",
    ),
    (
        "post_delta_out_of_int64_range",
        [(0, b'{"n":"5"}')],
        ("POST", "/data", b'{"n":"99999999999999999999"}'),
        (
            500,
            b'strconv.Atoi: parsing "99999999999999999999": value out of '
            b"range",
            TEXT,
        ),
        "main.go:200-203 with strconv's ErrRange: Go ints are 64-bit; "
        "Python's are not, so the oracle bounds-checks explicitly",
    ),
    (
        "get_state_backspace_escaping",
        [(0, b'{"s":"a\\bb"}')],
        ("GET", "/data", None),
        (
            200,
            b'{"s":"a\\u0008b"}',
            "application/json; charset=utf-8",
        ),
        "encoding/json gives only \\n \\r \\t short escapes; \\b must be "
        "\\u0008 (Python's json.dumps would emit \\b)",
    ),
    (
        "post_numeric_sum",
        [(0, b'{"n":"5"}'), (10, b'{"n":"-3"}')],
        ("GET", "/data", None),
        (200, b'{"n":"2"}', "application/json; charset=utf-8"),
        "main.go:195-206: both parse -> Itoa(curr+change) (eager fold)",
    ),
    (
        "get_state_sorted_keys",
        [(0, b'{"b":"1"}'), (10, b'{"a":"2"}')],
        ("GET", "/data", None),
        (200, b'{"a":"2","b":"1"}', "application/json; charset=utf-8"),
        "encoding/json sorts map keys lexicographically (c.JSON, "
        "main.go:132); no whitespace",
    ),
    (
        "get_state_html_escaping",
        [(0, b'{"s":"a<b&c>d"}')],
        ("GET", "/data", None),
        (
            200,
            b'{"s":"a\\u003cb\\u0026c\\u003ed"}',
            "application/json; charset=utf-8",
        ),
        "gin c.JSON uses encoding/json's default HTML escaping "
        "(main.go:132)",
    ),
    (
        "gossip_wire_shape",
        [(0, b'{"x":"5"}'), (10, b'{"y":"-3"}')],
        ("GET", "/gossip", None),
        (
            200,
            b'{"1000000":{"x":"5"},"1000010":{"y":"-3"}}',
            "application/json",
        ),
        "main.go:159 Diff.ToJSON() -> full log as {\"<ms>\": {k: v}}; "
        "main.go:163 sets Content-Type by hand (no charset); "
        "main.go:164 c.String of the bytes",
    ),
]


@pytest.mark.parametrize(
    "name,setup,request_,want,citation",
    FIXTURES,
    ids=[f[0] for f in FIXTURES],
)
def test_golden(shim, name, setup, request_, want, citation):
    u = shim.urls[0]
    clock = shim.nodes[0].clock
    for advance_ms, body in setup:
        clock.advance(advance_ms)
        _req(u + "/data", "POST", body)
    method, path, body = request_
    status, got_body, ctype = _req(u + path, method, body)
    want_status, want_body, want_ctype = want
    assert (status, got_body) == (want_status, want_body), citation
    assert ctype == want_ctype, citation


def test_gossip_keys_are_string_ordered():
    """main.go:159: treemap.ToJSON marshals via map[string]interface{},
    so the JSON object is ordered by the STRING form of the ms keys —
    "1000" sorts before "999".  (Irrelevant for same-epoch 13-digit
    timestamps, where string order == numeric order, but it is what the
    source does and the shim must match it byte-for-byte.)"""
    c = OracleHttpCluster(n=1, clock=ManualClock(start=999))
    c.start()
    try:
        u = c.urls[0]
        _req(u + "/data", "POST", b'{"a":"1"}')   # ts 999
        c.nodes[0].clock.advance(1)
        _req(u + "/data", "POST", b'{"b":"2"}')   # ts 1000
        _, wire, _ = _req(u + "/gossip")
        assert wire == b'{"1000":{"b":"2"},"999":{"a":"1"}}'
    finally:
        c.stop()


def test_gossip_null_entry_roundtrip(shim):
    """The invalid-body Put (main.go:187) leaves a nil *Command in the log;
    ToJSON marshals it as null (main.go:159).  A peer unmarshals null into
    a nil map[string]string (main.go:245-246), adopts it (main.go:68), and
    its rebuild ranges over the nil map as a no-op (main.go:80-81) — so
    null entries travel the wire forever but never affect state."""
    u = shim.urls[0]
    _req(u + "/data", "POST", b"not json")
    _, wire, _ = _req(u + "/gossip")
    assert wire == b'{"1000000":null}'
    # a second shim node adopts the null entry without error, state empty
    peer = OracleHttpCluster(n=1, clock=ManualClock(start=2_000_000))
    peer.start()
    try:
        pu = peer.urls[0]
        # peer needs a NEWER local entry for the two-pointer walk to adopt
        # the older null (tail-drop, main.go:49)
        _req(pu + "/data", "POST", b'{"z":"9"}')
        peer.nodes[0].receive_wire(wire.decode())
        _, state, _ = _req(pu + "/data")
        # null adopted silently; own z excluded after merge (quirk 0.1.1)
        assert json.loads(state) == {}
        _, peer_wire, _ = _req(pu + "/gossip")
        assert b'"1000000":null' in peer_wire
    finally:
        peer.stop()


def test_dead_node_502_everywhere(shim):
    """Alive=false (the merge window, main.go:41, or fault injection as
    INTENDED by main.go:150): every surface 502s with "Unreachable" —
    ping main.go:123, GET /data main.go:135, gossip main.go:167, POST
    /data main.go:211."""
    shim.nodes[0].oracle.alive = False
    u = shim.urls[0]
    for method, path, body in [
        ("GET", "/ping", None),
        ("GET", "/data", None),
        ("GET", "/gossip", None),
        ("POST", "/data", b'{"x":"1"}'),
    ]:
        status, got, ctype = _req(u + path, method, body)
        assert (status, got) == (502, b"Unreachable"), path
        assert ctype == TEXT, path
