"""Live divergence audit plane tests (crdt_tpu.obs.audit +
crdt_tpu.ops.digest).

The plane's contract has two halves and the tests pin both:

* **no false positives** — the digest is order-independent and the
  frontier clamp makes it delivery-schedule-independent, so correct
  replicas NEVER disagree at a shared frontier (duplicates, reorders,
  clock skew, in-flight ops notwithstanding), and the incremental
  accumulator never drifts from the from-scratch recompute across any
  state transition (merge, fold, summary adoption, checkpoint restore);

* **no false negatives for the planted class** — a silent winner-ts
  flip behind the digest's back is convicted by the scrub, surfaces as
  a ``divergence_detected`` event at the shared frontier, latches the
  watchdog at AUDIT_DIVERGED, and auto-captures exactly one postmortem
  bundle carrying the digest witnesses.

The wire side (digest piggybacked on the existing gossip response's
stability header — zero new round trips) is pinned against a live
NodeHost; the full fleet-scale chain runs in the nemesis soak's
``--audit`` arm.
"""
from __future__ import annotations

import json
import random
import tarfile
import threading
import urllib.request

import numpy as np
import pytest

from crdt_tpu.api.node import ReplicaNode, pull_round
from crdt_tpu.obs import audit
from crdt_tpu.obs.registry import MetricsRegistry
from crdt_tpu.obs.trace import mint_trace_id
from crdt_tpu.ops import digest as digops
from crdt_tpu.utils import checkpoint as ckpt
from crdt_tpu.utils.clock import HostClock
from crdt_tpu.utils.metrics import Metrics


def _node(rid: int, clock: HostClock | None = None) -> ReplicaNode:
    return ReplicaNode(rid=rid, capacity=64, clock=clock or HostClock(),
                       metrics=Metrics(registry=MetricsRegistry()))


def _pull(dst: ReplicaNode, src: ReplicaNode, fetch=None) -> None:
    pull_round(dst, fetch or src.gossip_payload, dst.metrics, delta=True,
               peer=str(src.rid), trace=mint_trace_id(dst.rid))


# ---------------------------------------------------------------- kernel


def test_row_hash_int_mirror_and_device_trace_bit_equal():
    """The three row-hash forms — pure-int host mirror, numpy scalar,
    and the jnp-traced kernel the mesh plane folds — agree bit-for-bit
    on random rows (negative rids and 64-bit timestamps included), and
    the lane fold commutes with batching on both backends."""
    import jax.numpy as jnp

    rng = random.Random(0xD16E57)
    rows = []
    for _ in range(64):
        key = f"k{rng.randrange(50)}"
        ts = rng.randrange(-2 ** 40, 2 ** 62)
        rid = rng.randrange(-5, 2 ** 31)
        seq = rng.randrange(0, 2 ** 33)
        rows.append((key, ts, rid, seq))

    np_rows, int_rows = [], []
    for key, ts, rid, seq in rows:
        kl = digops.key_lanes(key)
        a = digops.row_lanes_one(kl, ts, rid, seq)
        b = digops.row_lanes_ints(digops.key_lanes_ints(key), ts, rid, seq)
        c = digops.row_lanes(
            jnp.asarray(kl),
            jnp.uint32(digops.fold_ts(ts)),
            jnp.uint32(rid & 0xFFFFFFFF),
            jnp.uint32(seq & 0xFFFFFFFF))
        assert tuple(int(v) for v in a) == b
        assert tuple(int(v) for v in np.asarray(c)) == b
        np_rows.append(a)
        int_rows.append(b)

    batch = np.stack(np_rows)
    host_sum = digops.lane_sum(batch)
    dev_sum = np.asarray(digops.lane_sum(jnp.asarray(batch)))
    assert np.array_equal(host_sum, dev_sum)
    acc = digops.ZERO_INTS
    for r in int_rows:
        acc = digops.add_lanes_ints(acc, r)
    assert digops.digest_hex(host_sum) == digops.digest_hex(acc)


def test_digest_order_independent_and_subtract_inverts():
    rng = random.Random(7)
    rows = [(digops.key_lanes_ints(f"k{i}"), 1000 + i, i % 3, i)
            for i in range(20)]
    accs = []
    for _ in range(5):
        rng.shuffle(rows)
        acc = digops.ZERO_INTS
        for kl, ts, rid, seq in rows:
            acc = digops.add_lanes_ints(
                acc, digops.row_lanes_ints(kl, ts, rid, seq))
        accs.append(acc)
    assert len(set(accs)) == 1
    kl = digops.key_lanes_ints("x")
    r = digops.row_lanes_ints(kl, 5, 1, 2)
    assert digops.sub_lanes_ints(
        digops.add_lanes_ints(accs[0], r), r) == accs[0]


def test_digest_hex_round_trip_and_garbage_rejected():
    acc = (1, 2, 0xFFFFFFFF, 0)
    s = digops.digest_hex(acc)
    assert len(s) == 32
    assert tuple(int(v) for v in digops.parse_digest_hex(s)) == acc
    for bad in (None, 7, "", "zz" * 16, s[:-1], s + "0"):
        assert digops.parse_digest_hex(bad) is None


# ------------------------------------------------- incremental upkeep


def _assert_no_drift(node: ReplicaNode, where: str) -> None:
    d = node.digest
    _w, _r, acc = d.compute_from_store()
    assert d.acc == acc, f"incremental digest drifted after {where}"


def test_incremental_digest_survives_every_state_transition(tmp_path):
    """acc == from-scratch recompute after local writes, merges, a
    compaction fold, summary adoption by a revived peer, and a
    checkpoint save/restore round trip — the transitions the soak's
    scrub oracle sweeps at fleet scale."""
    clock = HostClock()
    a, b = _node(0, clock), _node(1, clock)
    a.enable_audit()
    b.enable_audit()

    for i in range(6):
        a.add_command({f"k{i % 4}": str(i)}, ts=i * 10)
    _assert_no_drift(a, "local writes")
    _pull(b, a)
    _assert_no_drift(b, "merge")
    b.add_command({"k9": "peer"}, ts=100)
    _pull(a, b)
    _assert_no_drift(a, "cross merge")

    f = a.version_vector()
    a.compact(f)
    _assert_no_drift(a, "fold")

    # summary adoption: a fresh node pulls from the compacted one and
    # adopts its frontier+summary wholesale
    fresh = _node(2, clock)
    fresh.enable_audit()
    _pull(fresh, a, fetch=lambda since=None: a.gossip_payload())
    _assert_no_drift(fresh, "summary adoption")
    assert fresh.audit_digest_at(f) == a.audit_digest_at(f)

    # checkpoint round trip rebuilds the digest from the restored store
    ckpt.save_node_atomic(str(tmp_path / "ck"), a)
    restored = _node(0, HostClock())
    restored.enable_audit()
    assert ckpt.load_latest_node(str(tmp_path / "ck"), restored)
    _assert_no_drift(restored, "checkpoint restore")
    assert restored.audit_digest_at(f) == a.audit_digest_at(f)


# ------------------------------------------------- frontier clamp


def test_frontier_clamp_comparable_under_skew_and_inflight_ops():
    """Replicas whose clocks disagree by seconds and whose op sets
    differ ABOVE the frontier still produce bit-identical digests AT
    the frontier; outside the soundness window (F below our compaction
    frontier, F ahead of our vv) the clamp refuses instead of lying."""
    a = _node(0, HostClock(epoch_ms=1_000_000))
    b = _node(1, HostClock(epoch_ms=1_004_321))  # 4.3s of skew
    a.enable_audit()
    b.enable_audit()
    for i in range(5):
        a.add_command({f"k{i}": str(i)}, ts=i * 10)
    b.add_command({"kb": "1"}, ts=7)
    _pull(b, a)
    _pull(a, b)
    f = a.version_vector()
    assert f == b.version_vector()
    a.compact(f)
    b.compact(f)
    assert a.audit_digest_at(f) == b.audit_digest_at(f) is not None

    # in-flight ops above F do not move the clamped digest
    before = a.audit_digest_at(f)
    a.add_command({"k0": "newer"}, ts=500)
    b.add_command({"zz": "other"}, ts=600)
    assert a.audit_digest_at(f) == before
    assert b.audit_digest_at(f) == before

    # refusal outside the window: ahead of vv / behind our own fold
    ahead = {r: s + 10 for r, s in a.version_vector().items()}
    assert a.audit_digest_at(ahead) is None
    assert a.audit_digest_at({}) is None  # below the compaction frontier


def test_duplicate_and_reordered_delivery_no_false_positive():
    """The guard the clamp exists for: one peer receives the payload
    TWICE, another receives it split in reverse order — all three
    digests agree at the shared frontier and the watchdog stays
    AUDIT_OK with zero divergences."""
    clock = HostClock()
    a, b, c = _node(0, clock), _node(1, clock), _node(2, clock)
    for n in (a, b, c):
        n.enable_audit()
    for i in range(8):
        a.add_command({f"k{i % 5}": str(i)}, ts=i * 10)

    full = a.gossip_payload()
    _pull(b, a)
    _pull(b, a, fetch=lambda since=None: dict(full))  # duplicate delivery
    items = sorted(full.items())
    part1 = dict(items[: len(items) // 2])
    part2 = dict(items[len(items) // 2:])
    _pull(c, a, fetch=lambda since=None: dict(part2))  # reordered halves
    _pull(c, a, fetch=lambda since=None: dict(part1))

    f = a.version_vector()
    for n in (a, b, c):
        assert n.version_vector() == f
        n.compact(f)
    assert a.audit_digest_at(f) == b.audit_digest_at(f) \
        == c.audit_digest_at(f)

    wd = audit.AuditWatchdog(b)
    for peer in (a, c):
        _vv, frontier, dig = peer.audit_snapshot()
        wd.note_host(f"http://{peer.rid}", frontier, dig)
    assert wd.state == audit.AUDIT_OK
    assert wd.divergences == []
    reg = b.metrics.registry
    assert reg.gauge_value("audit_state") == audit.AUDIT_OK
    assert reg.gauge_value("audit_agreement", plane="host") == 1.0


# ------------------------------------------------- planted divergence


def test_planted_flip_convicted_detected_and_postmortem(tmp_path):
    """The 1:1 chain on two live nodes: plant a silent winner-ts flip
    on a, the scrub convicts it (and ONLY it — b scrubs clean), b's
    watchdog sees the disagreement at the shared frontier, emits
    divergence_detected, latches AUDIT_DIVERGED, and writes exactly one
    postmortem bundle carrying the digest witnesses."""
    clock = HostClock()
    a, b = _node(0, clock), _node(1, clock)
    a.enable_audit()
    b.enable_audit()
    for i in range(6):
        a.add_command({f"k{i % 3}": str(i)}, ts=i * 10)
    _pull(b, a)
    f = a.version_vector()
    a.compact(f)
    b.compact(f)

    log = tmp_path / "events.jsonl"
    log.write_text(json.dumps({"event": "boot", "node": "1"}) + "\n")
    wd = audit.AuditWatchdog(b)
    wd.configure_postmortem(str(tmp_path), seed=7, log_paths=[str(log)])

    # agreement first: the divergence below must be a state CHANGE
    _vv, fr, dig = a.audit_snapshot()
    wd.note_host("http://a", fr, dig)
    assert wd.state == audit.AUDIT_OK

    witness = audit.plant_divergence(a)
    assert witness is not None and witness["ts_after"] > witness["ts_before"]
    # the flip is invisible until the scrub adopts it into the served
    # digest; b's own store is untouched and must scrub clean
    assert a.audit_scrub() is True
    assert b.audit_scrub() is False

    _vv, fr2, dig2 = a.audit_snapshot()
    assert fr2 == fr and dig2 != dig
    wd.note_host("http://a", fr2, dig2)

    assert wd.state == audit.AUDIT_DIVERGED
    [div] = wd.divergences
    assert div["plane"] == "host"
    assert {div["a"], div["b"]} == {"http://a", "local"}
    [ev] = list(b.events.find(event="divergence_detected"))
    assert ev["plane"] == "host"
    assert b.metrics.registry.gauge_value("audit_state") \
        == audit.AUDIT_DIVERGED

    bundle = tmp_path / "postmortem-7.tar.gz"
    assert wd.postmortem_path == str(bundle) and bundle.exists()
    with tarfile.open(bundle) as tf:
        names = tf.getnames()
        member = next(n for n in names if n.endswith("audit_witnesses.json"))
        wit = json.loads(tf.extractfile(member).read())
    assert wit["divergence"]["plane"] == "host"
    assert wit["planes"]["host"]["digest"] in (dig, dig2)

    # latched: a second disagreeing frontier adds provenance but never a
    # second bundle, and the state cannot un-diverge
    a.add_command({"k0": "more"}, ts=900)
    _pull(b, a)
    f3 = a.version_vector()
    a.compact(f3)
    b.compact(f3)
    _vv, fr3, dig3 = a.audit_snapshot()
    wd.note_host("http://a", fr3, dig3)
    assert wd.state == audit.AUDIT_DIVERGED
    assert wd.postmortem_path == str(bundle)
    assert len(list(tmp_path.glob("postmortem-*.tar.gz"))) == 1


def test_plant_divergence_is_rid_keyed_and_value_invisible():
    """Two replicas planting 'the same' corruption must NOT agree on
    the wrong answer: the bump is rid-keyed, so same-key plants on
    different nodes produce different wrong digests (a fixed bump would
    manufacture consistently-wrong-but-agreeing replicas the audit
    plane could never catch).  And the plant never touches values —
    get_state stays identical, only the audit plane can see it."""
    clock = HostClock()
    a, b = _node(0, clock), _node(1, clock)
    a.enable_audit()
    b.enable_audit()
    a.add_command({"k": "v"}, ts=10)
    _pull(b, a)
    f = a.version_vector()
    a.compact(f)
    b.compact(f)
    state_before = a.get_state()

    wa = audit.plant_divergence(a)
    wb = audit.plant_divergence(b)
    assert wa["key"] == wb["key"] == "k"
    assert wa["ts_after"] != wb["ts_after"]
    a.audit_scrub()
    b.audit_scrub()
    assert a.audit_digest_at(f) != b.audit_digest_at(f)
    assert a.get_state() == state_before  # values untouched


# ------------------------------------------------- continuous evaluators


def test_scrub_cadence_and_frontier_stall_edge_trigger():
    class StubTracker:
        def __init__(self):
            self.stale = ["http://peer"]

        def stale_members(self):
            return list(self.stale)

    n = _node(0)
    n.enable_audit()
    n.add_command({"k": "v"}, ts=1)
    tracker = StubTracker()
    wd = audit.AuditWatchdog(n, stability=tracker, scrub_every=4,
                             stall_rounds=3)
    for _ in range(12):
        wd.evaluate()
    assert wd.evals == 12 and wd.scrub_drifts == []
    # stall fired once (edge-triggered) despite 12 stale rounds
    stalls = list(n.events.find(event="audit_frontier_stall"))
    assert len(stalls) == 1 and stalls[0]["stale"] == ["http://peer"]
    # recovery re-arms the trigger
    tracker.stale = []
    for _ in range(3):
        wd.evaluate()
    tracker.stale = ["http://peer"]
    for _ in range(3):
        wd.evaluate()
    assert len(list(n.events.find(event="audit_frontier_stall"))) == 2


# ------------------------------------------------- checkpoint verification


def test_shard_restore_preserves_absolute_ts_across_boot_epochs(tmp_path):
    """Checkpoint round trip under REAL clocks whose epochs differ
    between boots (the rebooted process starts later, so its fresh
    HostClock epoch is ahead of the saved one).  The shard replay must
    run under the SAVED epoch — replaying under the boot epoch and
    swapping epochs afterwards shifts every restored op's absolute
    timestamp by the boot gap, making the rebooted replica silently
    disagree with peers about ops it acked pre-crash.  The restore-time
    digest verification is what catches that class; this pins it with
    an explicit row-level witness."""
    from crdt_tpu.keyspace import ShardedKeyspace, qualify

    def winners(shard):
        pd = (shard.digest if shard.digest is not None
              else audit.PlaneDigest(shard))
        winner, _rows, _acc = pd.compute_from_store()
        return winner

    e1 = HostClock().epoch_ms  # first boot's wall-anchored epoch
    host = _node(0, HostClock(epoch_ms=e1))
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=64,
                         clock=HostClock(epoch_ms=e1))
    for i in range(12):
        qkey = qualify("t", f"k{i:02d}")
        assert ks.shards[ks.shard_of("t", f"k{i:02d}")].add_command(
            {qkey: f"v{i}"})
    before = [winners(s) for s in ks.shards]

    path = str(tmp_path / "snap")
    ckpt.save_node(path, host, keyspace=ks)

    # the "rebooted five seconds later" incarnation
    host2 = _node(0, HostClock(epoch_ms=e1 + 5_000))
    ks2 = ShardedKeyspace(rid=0, n_shards=2, capacity=64,
                          clock=HostClock(epoch_ms=e1 + 5_000))
    ckpt.restore_node(path, host2, keyspace=ks2)  # digest check inside
    after = [winners(s) for s in ks2.shards]
    assert after == before  # absolute (ts, rid, seq) rows bit-identical
    for s_old, s_new in zip(ks.shards, ks2.shards):
        assert (audit.store_digest_hex(s_new)
                == audit.store_digest_hex(s_old))


def test_checkpoint_digest_mismatch_quarantines_generation(tmp_path):
    """A snapshot whose stores were corrupted AFTER the manifest was
    written (the class SHA-256 cannot see: the tamper re-signs) fails
    the restore-time digest verification, is quarantined, and restore
    falls back to the previous intact generation."""
    root = tmp_path / "ck"
    a = _node(0)
    for i in range(4):
        a.add_command({f"k{i}": str(i)}, ts=i * 10)
    a.compact(a.version_vector())
    good_digest = audit.store_digest_hex(a)
    ckpt.save_node_atomic(str(root), a)

    a.add_command({"k9": "newer"}, ts=100)
    snap = ckpt.save_node_atomic(str(root), a)
    meta_path = tmp_path / "ck" / snap.split("/")[-1] / "meta.json"
    meta = json.loads(meta_path.read_text())
    meta["summary"]["k0"]["ts"] = int(meta["summary"]["k0"]["ts"]) + 1
    meta_path.write_text(json.dumps(meta))
    ckpt.write_manifest(str(meta_path.parent))  # tamper re-signs the SHAs

    restored = _node(0)
    assert ckpt.load_latest_node(str(root), restored)
    # the corrupt generation was quarantined with the digest as reason...
    [q] = list(restored.events.find(event="snapshot_quarantine"))
    assert "digest" in q["reason"]
    # ...and the restore landed on the previous generation, intact
    assert audit.store_digest_hex(restored) == good_digest
    assert "k9" not in restored.get_state()


# ------------------------------------------------- wire piggyback


def test_gossip_response_piggybacks_digest_no_new_round_trips():
    """The digest rides the SAME stability header every gossip response
    already carries (frontier-paired, so the receiver compares at the
    serving node's exact clamp), and GET /audit serves the watchdog
    report — the fleet-scale census equality (a planted arm's wire-call
    histogram bit-equal to a digest-free arm's) runs in the soak."""
    from crdt_tpu.api.net import NodeHost
    from crdt_tpu.consistency.stability import (STABILITY_HEADER,
                                                decode_summary)
    from crdt_tpu.utils.config import ClusterConfig

    h = NodeHost(rid=0, peers=[], config=ClusterConfig())
    threading.Thread(target=h._server.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            h.url + "/data", data=json.dumps({"k": "v"}).encode(),
            method="POST")
        assert urllib.request.urlopen(req, timeout=5).status == 200
        resp = urllib.request.urlopen(h.url + "/gossip", timeout=5)
        summary = decode_summary(resp.headers.get(STABILITY_HEADER))
        vv, frontier, dig = h.node.audit_snapshot()
        assert summary is not None and summary.get("digest") == dig

        report = json.loads(urllib.request.urlopen(
            h.url + "/audit", timeout=5).read())
        assert report["node"] == "0"
        assert report["state"] in (audit.AUDIT_NO_DATA, audit.AUDIT_OK)
        assert "host" in report["planes"]
        assert report["planes"]["host"]["digest"] == dig
    finally:
        h._server.shutdown()
        h._server.server_close()


# ------------------------------------------------- offline cross-check


def test_cross_check_groups_by_exact_frontier():
    rep = {
        "digest": "0" * 32, "frontier": {"0": 5},
    }
    agree = audit.cross_check({
        "a": {"planes": {"host": dict(rep)}},
        "b": {"planes": {"host": dict(rep)}},
    })
    [row] = [r for r in agree if r["n"] == 2]
    assert row["agree"] is True
    bad = audit.cross_check({
        "a": {"planes": {"host": dict(rep)}},
        "b": {"planes": {"host": {"digest": "f" * 32,
                                  "frontier": {"0": 5}}}},
        "c": {"planes": {"host": {"digest": "0" * 32,
                                  "frontier": {"0": 6}}}},  # other frontier
    })
    flagged = [r for r in bad if r["agree"] is False]
    assert len(flagged) == 1
    assert sorted(flagged[0]["digests"]) == ["a", "b"]
    # the other-frontier report lands in its OWN single-member row —
    # never compared against the ("0", 5) pair
    assert any(r["n"] == 1 and r["frontier"] == {"0": 6} for r in bad)
