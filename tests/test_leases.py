"""Coordinator leases + fencing tokens (consistency/leases.py): the
deterministic fake-clock half of what ``nemesis_soak --strong
--crash-coordinator`` hammers end-to-end.

Every manager runs on a manual clock that only moves when a test moves
it, and every "wire" grant lands directly on the target manager's voter
side — so the double-holder, expiry, handoff, and clock-skew scenarios
here are exact, not raced.
"""
from __future__ import annotations

import pytest

from crdt_tpu.consistency.leases import (
    LEASE_STATE,
    LeaseManager,
    slot_of_key,
)
from crdt_tpu.keyspace.routing import RendezvousRouter, ranked_members
from crdt_tpu.obs.events import EventLog


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t


class CountMetrics:
    def __init__(self):
        self.counts = {}

    def inc(self, name, *a, **kw):
        self.counts[name] = self.counts.get(name, 0) + 1


class LeasePeer:
    """RemotePeer stand-in: ``lease_grant`` lands on the target
    manager's voter side exactly like POST /lease/grant, with switches
    for a dead transport and an open breaker."""

    def __init__(self, mgr: LeaseManager, url: str):
        self.mgr = mgr
        self.url = url
        self.backed = False
        self.down = False
        self.grant_calls = 0

    def backed_off(self) -> bool:
        return self.backed

    def backoff_peek(self) -> bool:
        return self.backed

    def lease_grant(self, *, slot, holder, fence, ttl):
        self.grant_calls += 1
        if self.down:
            return None
        return self.mgr.grant(slot, holder, fence, ttl)


def mk_cluster(n: int, *, duration: float = 10.0, shared_clock=True):
    """n managers fully meshed over LeasePeers.  Returns (managers,
    clocks, peer-matrix); with ``shared_clock`` every node reads ONE
    clock, else each node gets its own (the skew tests)."""
    clocks = [ManualClock() for _ in range(n)]
    if shared_clock:
        clocks = [clocks[0]] * n
    mgrs = [
        LeaseManager(None, n_slots=4, duration=duration,
                     clock=clocks[i].now, events=EventLog(node=f"n{i}"),
                     metrics=CountMetrics())
        for i in range(n)
    ]
    peers = {
        i: [LeasePeer(mgrs[j], f"http://n{j}") for j in range(n) if j != i]
        for i in range(n)
    }
    for i, m in enumerate(mgrs):
        m.attach(f"http://n{i}", (lambda i=i: peers[i]))
    return mgrs, clocks, peers


def holders(mgrs, slot):
    return [i for i, m in enumerate(mgrs) if m.held_fence(slot) is not None]


# ------------------------------------------------------------ routing


def test_slot_of_key_deterministic_and_in_range():
    for key in ("reg-a", "reg-b", "user:42", ""):
        s = slot_of_key(key, 8)
        assert 0 <= s < 8
        assert s == slot_of_key(key, 8)  # no per-process salt
    # a realistic key pool should not collapse onto one slot
    assert len({slot_of_key(f"k{i}", 8) for i in range(64)}) > 1


def test_coordinator_view_is_shared_across_members():
    mgrs, _, _ = mk_cluster(3)
    for slot in range(4):
        views = {m.coordinator_of(slot) for m in mgrs}
        assert len(views) == 1, (
            f"slot {slot}: members disagree on the coordinator with "
            f"identical live views: {views}"
        )


def test_rendezvous_seam_matches_router_for_urls():
    """Cross-use determinism (ISSUE satellite): the lease plane's
    ranked_members and the keyspace's RendezvousRouter are ONE seam —
    same members + same key -> same ranking, whether the members are
    shard names or node URLs."""
    for members in (
        [f"shard-{i}" for i in range(5)],
        [f"http://10.0.0.{i}:8430" for i in range(1, 6)],
        ["http://n0", "http://n1", "http://n2"],
    ):
        router = RendezvousRouter(members)
        for key in ("reg-a", "lease-slot-3", "tenant\x00k1", "x"):
            assert router.ranked(key) == ranked_members(members, key)
            assert router.owner(key) == ranked_members(members, key)[0]


def test_ranked_members_ident_ranks_over_stable_names():
    """With ``ident``, the weight is computed over the stable name while
    the returned values stay the member strings — two member lists that
    map to the same idents rank identically (port-blind routing)."""
    ident_a = {"http://h:1111": "member-0", "http://h:2222": "member-1"}
    ident_b = {"http://h:9999": "member-0", "http://h:8888": "member-1"}
    for key in ("lease-slot-0", "lease-slot-1", "reg-c"):
        ra = ranked_members(sorted(ident_a), key, ident=ident_a.get)
        rb = ranked_members(sorted(ident_b), key, ident=ident_b.get)
        assert [ident_a[m] for m in ra] == [ident_b[m] for m in rb]


# ------------------------------------------------------- no double holder


def test_single_holder_while_lease_unexpired():
    mgrs, _, _ = mk_cluster(3)
    slot = 0
    fence = mgrs[0].ensure(slot)
    assert fence == 1
    assert holders(mgrs, slot) == [0]
    # every other member is refused while the grant is unexpired: their
    # acquisition must NOT spin past the live holder
    assert mgrs[1].ensure(slot) is None
    assert mgrs[2].ensure(slot) is None
    assert holders(mgrs, slot) == [0]
    # the holder's own ensure is a no-wire fast path inside half-life
    assert mgrs[0].ensure(slot) == 1


def test_reacquire_before_expiry_keeps_same_fence():
    mgrs, clocks, _ = mk_cluster(3, duration=10.0)
    slot = 1
    assert mgrs[0].ensure(slot) == 1
    clocks[0].t = 6.0  # past half-life: ensure renews through the quorum
    assert mgrs[0].ensure(slot) == 1
    # renewal re-extended expiry: still held well past the original ttl
    clocks[0].t = 12.0
    assert mgrs[0].held_fence(slot) == 1


# ------------------------------------------------------ expiry + renewal


def test_expiry_mid_renewal_keeps_lease_until_ttl_then_drops():
    """A coordinator cut off from the quorum keeps its lease only until
    ttl: failed renewals never self-extend, and after expiry the
    acquisition path needs a quorum it cannot reach."""
    mgrs, clocks, peers = mk_cluster(3, duration=10.0)
    slot = 2
    assert mgrs[0].ensure(slot) == 1
    for p in peers[0]:
        p.down = True  # transport dead: renewal votes go unanswered
    clocks[0].t = 6.0  # past half-life -> renewal round fails
    assert mgrs[0].ensure(slot) == 1, (
        "failed renewal must keep the still-unexpired lease"
    )
    assert mgrs[0].metrics.counts.get("lease_renew_failures", 0) >= 1
    clocks[0].t = 10.0  # ttl reached: the lease lapses, loudly
    assert mgrs[0].held_fence(slot) is None
    assert mgrs[0].events.find(event="lease_expire")
    assert mgrs[0].ensure(slot) is None, (
        "an isolated coordinator must not re-acquire without a quorum"
    )


def test_handoff_after_expiry_bumps_fence():
    mgrs, clocks, _ = mk_cluster(3, duration=10.0)
    slot = 0
    assert mgrs[0].ensure(slot) == 1
    clocks[0].t = 11.0  # everyone agrees the grant lapsed
    f2 = mgrs[1].ensure(slot)
    assert f2 == 2, "the successor must open a NEW fence epoch"
    assert holders(mgrs, slot) == [1]
    # the old holder's stamp is now refused wherever the new fence is
    # known — the zombie firewall the push path leans on
    verdict = mgrs[1].check_push_fences({slot: 1})
    assert verdict == {"slot": slot, "fence": 2}
    assert mgrs[1].events.find(event="cas_fenced_reject")


def test_fence_monotone_across_repeated_handoffs():
    mgrs, clocks, _ = mk_cluster(3, duration=10.0)
    slot = 3
    fences = []
    for round_i in range(6):
        owner = round_i % 3
        f = mgrs[owner].ensure(slot)
        assert f is not None
        fences.append(f)
        clocks[0].t += 11.0  # lapse, next round's owner takes over
    assert fences == sorted(fences)
    assert len(set(fences)) == len(fences), (
        f"fence epochs repeated across handoffs: {fences}"
    )


def test_restored_fences_keep_refusing_after_crash():
    """Fail-stop persistence: a rebooted voter restored from its
    checkpointed fence floor refuses stale stamps it refused before,
    and proposers start above the floor."""
    mgrs, clocks, _ = mk_cluster(3, duration=10.0)
    slot = 0
    for _ in range(3):
        mgrs[0].ensure(slot)
        clocks[0].t += 11.0
        mgrs[1].ensure(slot)
        clocks[0].t += 11.0
    snap = mgrs[1].fences_snapshot()
    reborn = LeaseManager(None, n_slots=4, duration=10.0,
                          clock=clocks[0].now,
                          events=EventLog(node="reborn"),
                          metrics=CountMetrics())
    reborn.restore_fences(snap)
    floor = snap[slot]
    assert floor >= 2
    assert reborn.fence_of(slot) == floor
    assert reborn.check_push_fences({slot: floor - 1}) is not None
    refused = reborn.grant(slot, "http://zombie", floor - 1, 10.0)
    assert not refused["granted"] and refused["fence"] == floor


# ------------------------------------------------------------ clock skew


def test_skewed_zombie_view_is_fenced_not_trusted():
    """Clock skew makes lease VIEWS diverge: the zombie's slow clock
    says 'held' long after the fleet moved on.  Routing views never
    arbitrate — the fence does: the successor holds a higher epoch, the
    zombie's stamp is refused, and learning the new fence self-heals
    the zombie's table."""
    mgrs, clocks, _ = mk_cluster(3, duration=10.0, shared_clock=False)
    slot = 0
    assert mgrs[0].ensure(slot) == 1
    # the fleet's clocks advance past the ttl; the zombie's stands still
    clocks[1].t = clocks[2].t = 12.0
    f2 = mgrs[1].ensure(slot)
    assert f2 == 2
    # BOTH tables now claim 'held' — exactly the double-view skew makes
    assert mgrs[0].held_fence(slot) == 1
    assert mgrs[1].held_fence(slot) == 2
    # ...but the zombie's stamp cannot pass any fence-aware replica
    assert mgrs[1].check_push_fences({slot: 1}) == {"slot": slot,
                                                    "fence": 2}
    assert mgrs[2].check_push_fences({slot: 1}) == {"slot": slot,
                                                    "fence": 2}
    # the refusal teaches the zombie the successor's fence: its stale
    # hold is dropped on the spot, no expiry wait needed
    mgrs[0].note_fence(slot, 2)
    assert mgrs[0].held_fence(slot) is None


def test_skewed_voter_refuses_equal_fence_other_holder():
    """Voter rule under skew: a voter whose grant has EXPIRED by its own
    clock still refuses an equal-fence proposal from a different holder
    — epochs are single-writer even when expiry views disagree."""
    mgrs, clocks, _ = mk_cluster(2, duration=10.0, shared_clock=False)
    slot = 1
    got = mgrs[1].grant(slot, "http://n0", 1, 10.0)
    assert got["granted"]
    # while the grant is live, the SAME holder renewing its epoch is fine
    renew = mgrs[1].grant(slot, "http://n0", 1, 10.0)
    assert renew["granted"]
    clocks[1].t = 20.0  # voter's view: that grant is long gone
    again = mgrs[1].grant(slot, "http://other", 1, 10.0)
    assert not again["granted"], (
        "fence 1 was burned by n0; a second holder at the same epoch "
        "would let two coordinators stamp identical tokens"
    )
    assert again["fence"] == 1
    # once expired, even the ORIGINAL holder cannot re-enter epoch 1:
    # the voter can no longer prove no one else burned it meanwhile
    stale = mgrs[1].grant(slot, "http://n0", 1, 10.0)
    assert not stale["granted"]


def test_taught_fence_retry_recovers_in_one_round():
    """A coordinator behind on fence gossip proposes low, is refused
    with the blocking fence named, and must recover by retrying ONCE
    above the taught value — not by spinning, not by giving up."""
    mgrs, _, peers = mk_cluster(3)
    slot = 2
    for m in mgrs[1:]:
        m.note_fence(slot, 7)  # the fleet knows an epoch mgr0 missed
    calls_before = [p.grant_calls for p in peers[0]]
    fence = mgrs[0].ensure(slot)
    assert fence == 8, f"expected one taught retry to land 8, got {fence}"
    rounds = sum(p.grant_calls for p in peers[0]) - sum(calls_before)
    assert rounds <= 4, (
        f"taught-fence recovery burned {rounds} grant calls; the retry "
        "must be bounded to one extra round"
    )


def test_slot_states_gauge_encoding():
    mgrs, clocks, _ = mk_cluster(3, duration=10.0)
    slot = 0
    assert mgrs[0].ensure(slot) == 1
    st = mgrs[0].slot_states()
    assert st[slot] == {"state": LEASE_STATE["held"], "fence": 1}
    assert all(v["state"] == LEASE_STATE["follower"]
               for s, v in st.items() if s != slot)
    clocks[0].t = 11.0
    assert (mgrs[0].slot_states()[slot]["state"]
            == LEASE_STATE["expired"])
    # held_fence observes the lapse -> the slot returns to follower
    assert mgrs[0].held_fence(slot) is None
    assert (mgrs[0].slot_states()[slot]["state"]
            == LEASE_STATE["follower"])


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
