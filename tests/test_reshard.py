"""Online keyspace resharding (crdt_tpu/keyspace/reshard.py): the
epoch-fenced live migration state machine.

What is pinned here, failure-mode first:

* crash mid-MIGRATE — a node checkpointed inside the window reboots,
  the restored ledger re-enters MIGRATE deterministically, and the
  resumed cutover lands the same tenant state the live one would have;
* ABORT — rolls back bit-identical (epoch, shard count, every shard's
  full wire dump) because nothing mutates before CUTOVER;
* stale-epoch fencing — every fenced wire surface (/ks/gossip,
  /ks/compact, /ks/migrate, the stamped /ingest/page admit) answers
  409 naming the CURRENT epoch, 1:1 with serve-side fence provenance;
* corrupt migration slices — quarantined whole, loudly, without
  wedging the window (the next clean slice folds, cutover proceeds);
* lock discipline — every refusal path leaves the coordinator lock,
  the door's admission lock, and the shard locks free (the CRDT210/212
  shapes: a leaked lock here wedges admissions forever).

The nemesis soak (--reshard) drives the same machine under a full
fault schedule; these tests are the deterministic, seed-free floor.
"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from crdt_tpu.api.net import NodeHost, RemotePeer
from crdt_tpu.keyspace import (ShardedKeyspace, TENANT_HEADER, qualify,
                               split_qualified)
from crdt_tpu.keyspace.reshard import PHASE_IDLE, PHASE_MIGRATE
from crdt_tpu.utils.config import ClusterConfig

KS_EPOCH_HEADER = "X-CRDT-KS-Epoch"

CFG = dict(keyspace_shards=2, keyspace_capacity=256)


def _serve(*hosts):
    for h in hosts:
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()


def _shutdown(*hosts):
    for h in hosts:
        h._server.shutdown()
        h._server.server_close()


def _post(url, body, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=5) as res:
        raw = res.read()
        try:
            return res.status, json.loads(raw or b"null")
        except json.JSONDecodeError:  # plain-text 200s ("OK")
            return res.status, raw.decode()


def _write(ks: ShardedKeyspace, tenant: str, key: str, value: str):
    qkey = qualify(tenant, key)
    assert ks.shards[ks.shard_of(tenant, key)].add_command({qkey: value})


def _ks_dump(ks: ShardedKeyspace):
    """The bit-identity witness: epoch + shard count + every shard's
    FULL wire dump (raw ops and folded summaries alike ride it)."""
    return (ks.epoch, ks.n_shards,
            [s.gossip_payload(since=None) for s in ks.shards])


# ---- ABORT: bit-identical rollback ----

def test_abort_rolls_back_bit_identical():
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=256)
    for i in range(24):
        _write(ks, "t-acme", f"k{i:03d}", f"v{i}")
    before = _ks_dump(ks)
    out = ks.reshard.start(4)
    assert out["phase"] == PHASE_MIGRATE and out["moved"] > 0
    # a peer's slice folds into the buffer — still pre-cutover, so the
    # abort must discard it along with the plan
    moved = [q for q in ks.state() if ks.reshard.moved_to(q) is not None]
    dst = ks.reshard.moved_to(moved[0])
    fold = ks.reshard.receive_migration(
        dst, {"1000:9:0": {moved[0]: "peer-value"}})
    assert fold["ok"] and fold["folded"] == 1
    assert ks.reshard.abort("test")["phase"] == PHASE_IDLE
    assert _ks_dump(ks) == before, "abort must be bit-identical"
    # idempotent: aborting an idle machine is a no-op status answer
    assert ks.reshard.abort("again")["phase"] == PHASE_IDLE
    # and the machine is reusable: a fresh window opens cleanly
    assert ks.reshard.start(4)["phase"] == PHASE_MIGRATE


# ---- crash mid-MIGRATE: ledger resume ----

def test_crash_mid_migrate_resumes_and_cuts_over(tmp_path):
    d = str(tmp_path / "ckpt")
    cfg = ClusterConfig(**CFG)
    a = NodeHost(rid=0, peers=[], config=cfg, checkpoint_dir=d)
    for i in range(20):
        _write(a.keyspace, "t-acme", f"k{i:03d}", f"v{i}")
    expect = a.keyspace.tenant_state("t-acme")
    assert a.admin_ks_reshard({"action": "start", "shards": 4})[
        "phase"] == PHASE_MIGRATE
    assert a.checkpoint_now() is not None  # the ledger rides the manifest
    a._server.server_close()  # SIGKILL analogue: no cutover ever ran

    b = NodeHost(rid=0, peers=[], config=cfg, checkpoint_dir=d)
    try:
        assert b.restored
        # the restored ledger re-entered MIGRATE deterministically
        st = b.keyspace.reshard.status()
        assert st["phase"] == PHASE_MIGRATE and st["target"] == 4
        assert b.keyspace.epoch == 0 and b.keyspace.n_shards == 2
        # ... and the resumed window cuts over to the same tenant state
        out = b.admin_ks_reshard({"action": "cutover"})
        assert out["epoch"] == 1 and out["n_shards"] == 4
        assert b.keyspace.tenant_state("t-acme") == expect
        # a settled post-cutover snapshot restores straight to S'=4 idle
        assert b.checkpoint_now() is not None
        b._server.server_close()
        c = NodeHost(rid=0, peers=[], config=cfg, checkpoint_dir=d)
        try:
            assert c.keyspace.n_shards == 4 and c.keyspace.epoch == 1
            assert c.keyspace.reshard.status()["phase"] == PHASE_IDLE
            assert c.keyspace.tenant_state("t-acme") == expect
        finally:
            c._server.server_close()
    except Exception:
        b._server.server_close()
        raise


# ---- stale-epoch 409 on every fenced surface ----

def test_stale_epoch_409_on_every_fenced_surface():
    from crdt_tpu.ingest import PageBuilder

    cfg = ClusterConfig(**CFG)
    a = NodeHost(rid=0, peers=[], config=cfg)
    _serve(a)
    try:
        _write(a.keyspace, "t-acme", "k0", "v0")
        a.admin_ks_reshard({"action": "start", "shards": 4})
        out = a.admin_ks_reshard({"action": "cutover"})
        assert out["epoch"] == 1 and out["n_shards"] == 4
        fences0 = a.keyspace.reshard.fences

        def expect_409(fn, surface):
            with pytest.raises(urllib.error.HTTPError) as ei:
                fn()
            assert ei.value.code == 409
            body = json.loads(ei.value.read())
            assert body["fenced"] is True and body["epoch"] == 1
            assert body["surface"] == surface
            return body

        # GET /ks/gossip — explicit stale epoch AND the pre-reshard
        # no-epoch client (treated as epoch 0: fenced after cutover)
        expect_409(lambda: urllib.request.urlopen(
            a.url + "/ks/gossip?shard=0&epoch=0", timeout=5), "ks_gossip")
        got = expect_409(lambda: urllib.request.urlopen(
            a.url + "/ks/gossip?shard=0", timeout=5), "ks_gossip")
        assert got["got"] == 0
        # POST /ks/compact — a frontier minted against the old planes
        expect_409(lambda: _post(a.url + "/ks/compact",
                                 {"shard": 0, "frontier": {},
                                  "epoch": 0}), "ks_compact")
        # POST /ks/migrate — a stale-epoch migration slice
        expect_409(lambda: _post(a.url + "/ks/migrate",
                                 {"shard": 0, "epoch": 0, "payload": {}}),
                   "ks_migrate")
        # POST /ingest/page — a stamped writer behind the map
        pager = PageBuilder(origin=7, page_size=1 << 16)
        pager.add("k1", "v1")
        raw = pager.flush()
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                a.url + "/ingest/page", data=raw, method="POST")
            req.add_header(TENANT_HEADER, "t-acme")
            req.add_header(KS_EPOCH_HEADER, "0")
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 409
        body = json.loads(ei.value.read())
        assert body["fenced"] is True and body["epoch"] == 1
        assert body["surface"] == "ingest_page"
        # every refusal black-boxed: serve-side fence count is 1:1
        assert a.keyspace.reshard.fences - fences0 == 5
        # the CURRENT epoch passes every surface it fenced
        assert urllib.request.urlopen(
            a.url + "/ks/gossip?shard=0&epoch=1", timeout=5).status == 200
        assert _post(a.url + "/ks/compact",
                     {"shard": 0, "frontier": {}, "epoch": 1})[0] == 200
        req = urllib.request.Request(
            a.url + "/ingest/page", data=raw, method="POST")
        req.add_header(TENANT_HEADER, "t-acme")
        req.add_header(KS_EPOCH_HEADER, "1")
        assert urllib.request.urlopen(req, timeout=5).status == 200
    finally:
        _shutdown(a)


# ---- corrupt migration slices: quarantined, never wedged ----

def test_corrupt_migration_slice_quarantined_without_wedging():
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=256)
    for i in range(16):
        _write(ks, "t-acme", f"k{i:03d}", f"v{i}")
    ks.reshard.start(4)
    moved = [q for q in ks.state() if ks.reshard.moved_to(q) is not None]
    dst = ks.reshard.moved_to(moved[0])
    q0 = ks.reshard.quarantines
    # malformed wire key (still valid JSON — the corrupt-fault shape)
    out = ks.reshard.receive_migration(
        dst, {"nemesis:corrupt:key": {moved[0]: "x"}})
    assert out["ok"] is False and "quarantined" in out
    # non-dict command
    out = ks.reshard.receive_migration(dst, {"1000:1:0": "not-a-dict"})
    assert out["ok"] is False and "quarantined" in out
    # a row routed at the WRONG destination: all-or-nothing, the whole
    # slice is refused even though other rows may be clean
    kept = next(q for q in ks.state()
                if ks.reshard.moved_to(q) is None)
    out = ks.reshard.receive_migration(
        dst, {"1000:1:0": {moved[0]: "a", kept: "b"}})
    assert out["ok"] is False and "quarantined" in out
    assert ks.reshard.quarantines - q0 == 3
    # the window is NOT wedged: a clean slice folds, cutover proceeds.
    # wire keys carry ABSOLUTE ms — year-2100 beats any local mint, so
    # the buffered peer candidate must win the LWW fold
    out = ks.reshard.receive_migration(
        dst, {"4102444800000:9:0": {moved[0]: "peer-wins"}})
    assert out["ok"] and out["folded"] == 1
    cut = ks.reshard.cutover()
    assert cut["epoch"] == 1 and cut["n_shards"] == 4
    tenant, key = split_qualified(moved[0])
    assert ks.get(tenant, key) == "peer-wins"


def test_receive_migration_outside_window_refuses():
    ks = ShardedKeyspace(rid=0, n_shards=2, capacity=64)
    out = ks.reshard.receive_migration(0, {"1:1:0": {"t:k": "v"}})
    assert out == {"ok": False, "reason": "not-migrating", "epoch": 0}
    assert ks.reshard.quarantines == 0  # a refusal, not a quarantine


# ---- lock discipline on the failure paths (CRDT210/212 shapes) ----

def _acquirable(lock, timeout=2.0) -> bool:
    """Prove the lock is FREE from another thread (an RLock re-acquired
    on the owning thread proves nothing)."""
    got = []

    def probe():
        ok = lock.acquire(timeout=timeout)
        if ok:
            lock.release()
        got.append(ok)

    t = threading.Thread(target=probe)
    t.start()
    t.join(timeout + 1)
    return bool(got and got[0])


def test_failure_paths_release_every_lock():
    cfg = ClusterConfig(**CFG)
    a = NodeHost(rid=0, peers=[], config=cfg)
    try:
        ks = a.keyspace
        for i in range(8):
            _write(ks, "t-acme", f"k{i}", "v")
        door = ks._door
        # refused start (already at target count)
        with pytest.raises(ValueError):
            ks.reshard.start(2)
        # cutover without a window
        with pytest.raises(ValueError):
            ks.reshard.cutover()
        # conflicting second target mid-window
        ks.reshard.start(4)
        with pytest.raises(ValueError):
            ks.reshard.start(3)
        # quarantined slice inside the window
        out = ks.reshard.receive_migration(99, {"1:1:0": {"t:k": "v"}})
        assert "quarantined" in out
        assert _acquirable(ks.reshard._phase_lock), "coordinator lock leaked"
        assert _acquirable(door._adm), "door admission lock leaked"
        for shard in ks.shards:
            assert _acquirable(shard._lock), "shard lock leaked"
        # and the happy path leaves them free too (cutover touches all)
        cut = ks.reshard.cutover()
        assert cut["epoch"] == 1
        assert _acquirable(ks.reshard._phase_lock)
        assert _acquirable(door._adm)
        for shard in ks.shards:
            assert _acquirable(shard._lock)
        # admissions still flow post-cutover: nothing wedged
        assert door.admit_kv("t-acme", "post", "cut") is not None
    finally:
        a._server.server_close()


# ---- two-node end-to-end over real sockets ----

def test_reshard_end_to_end_over_http():
    """The whole arc on real sockets: write on A, open the window on
    both, stream A's slices, cut both over, and assert S'=4 serves the
    same tenant state at epoch 1 — then post-cutover anti-entropy still
    converges fresh writes."""
    cfg = ClusterConfig(**CFG)
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[], config=cfg)
    _serve(a, b)
    try:
        a.agent.peers = [RemotePeer(b.url)]
        b.agent.peers = [RemotePeer(a.url)]
        for i in range(20):
            _write(a.keyspace, "t-acme", f"k{i:03d}", f"v{i}")
        assert b.agent.ks_pull(b.agent.peers[0]) == 20
        expect = a.keyspace.tenant_state("t-acme")
        # open the window everywhere, then stream (a not-yet-started
        # receiver would 409 the slices as not-migrating)
        for h in (a, b):
            assert _post(h.url + "/admin/ks_reshard",
                         {"action": "start", "shards": 4})[1][
                "phase"] == PHASE_MIGRATE
        stats = _post(a.url + "/admin/ks_reshard",
                      {"action": "stream"})[1]
        assert stats["sent"] > 0 and stats["failed"] == 0
        assert stats["ok"] == stats["sent"]
        for h in (a, b):
            out = _post(h.url + "/admin/ks_reshard",
                        {"action": "cutover"})[1]
            assert out["epoch"] == 1 and out["n_shards"] == 4
            assert h.keyspace.tenant_state("t-acme") == expect
        # fresh planes, fresh writes: ordinary anti-entropy at epoch 1
        _write(a.keyspace, "t-acme", "post-cutover", "yes")
        assert b.agent.ks_pull(b.agent.peers[0]) > 0
        assert b.keyspace.get("t-acme", "post-cutover") == "yes"
    finally:
        _shutdown(a, b)
