"""Fault plane: per-injector pins, SHA-256 manifest round-trip, the
LATEST-fallback recovery path, circuit-breaker half-open transitions,
schedule determinism, and a small end-to-end nemesis smoke."""
import json
import pathlib
import random
import time

import pytest

from crdt_tpu.api.net import NetworkAgent, NodeHost, RemotePeer
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.faults import (
    FaultPlane,
    FaultRule,
    FaultyDisk,
    FaultyTransport,
    NemesisSchedule,
    fsync_stall,
    plant_corruption,
    point_latest_at_missing,
    tear_snapshot,
)
from crdt_tpu.obs import health
from crdt_tpu.utils import checkpoint
from crdt_tpu.utils.config import ClusterConfig
from crdt_tpu.utils.metrics import Metrics


def _events(node, name):
    return [e for e in node.events.tail(100) if e.get("event") == name]


def _plane(*rules, seed=0):
    return FaultPlane(NemesisSchedule(
        seed=seed, steps=1000, nodes=2, rules=tuple(rules), skews=(),
    ))


@pytest.fixture
def served():
    """One serving NodeHost with a little state (the gossip source)."""
    host = NodeHost(rid=1, peers=[], port=0)
    host.node.add_command({"x": "1"}, ts=10)
    host.node.add_command({"y": "2"}, ts=11)
    host.start_server()
    yield host
    host.stop_server()


def _puller(rid=0):
    node = ReplicaNode(rid=rid, capacity=64)
    agent = NetworkAgent(node, [], ClusterConfig())
    return node, agent


# ---- snapshot integrity: manifest round-trip + fallback restore ----


def test_manifest_roundtrip_and_tamper_detection(tmp_path):
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "5"}, ts=10)
    snap = checkpoint.save_node_atomic(tmp_path, n)
    manifest = json.loads(
        (pathlib.Path(snap) / checkpoint.MANIFEST_NAME).read_text())
    assert set(manifest["files"]) == {"log.npz", "meta.json"}
    assert checkpoint.verify_snapshot(snap) is None

    torn_file = tear_snapshot(snap, rng=random.Random("t"))
    assert checkpoint.verify_snapshot(snap) == f"digest mismatch: {torn_file}"
    (pathlib.Path(snap) / torn_file).unlink()
    assert checkpoint.verify_snapshot(snap) == (
        f"manifest file missing: {torn_file}")
    assert checkpoint.verify_snapshot(tmp_path / "nope") == (
        "missing snapshot directory")


def test_latest_pointing_at_missing_dir_falls_back(tmp_path):
    """Kill between prune and repoint: LATEST names a dir that is gone —
    boot must restore the newest surviving snap, not crash."""
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "5"}, ts=10)
    checkpoint.save_node_atomic(tmp_path, n)
    point_latest_at_missing(tmp_path)

    n2 = ReplicaNode(rid=0, capacity=32)
    assert checkpoint.load_latest_node(tmp_path, n2)
    assert n2.get_state() == {"x": "5"}
    [q] = _events(n2, "snapshot_quarantine")
    assert q["reason"] == "missing snapshot directory"
    [r] = _events(n2, "snapshot_restore")
    assert r["fallback"] and r["verified"]


def test_corrupt_latest_restores_previous_generation(tmp_path):
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "5"}, ts=10)
    checkpoint.save_node_atomic(tmp_path, n)
    n.add_command({"y": "9"}, ts=11)
    checkpoint.save_node_atomic(tmp_path, n)
    torn = plant_corruption(tmp_path)  # tears the LATEST target
    torn_name = pathlib.Path(torn).name

    n2 = ReplicaNode(rid=0, capacity=32)
    assert checkpoint.load_latest_node(tmp_path, n2)
    # the torn generation (holding y) is quarantined; the previous one
    # restores — losing y, which only ever lived in the damaged snap
    assert n2.get_state() == {"x": "5"}
    assert n2.metrics._counts["snapshot_quarantines"] == 1
    assert n2.metrics._counts["snapshot_restores"] == 1
    [q] = _events(n2, "snapshot_quarantine")
    assert q["snap"] == torn_name and "digest mismatch" in q["reason"]
    [r] = _events(n2, "snapshot_restore")
    assert r["fallback"] and r["verified"] and r["snap"] != torn_name
    # the damaged dir left the snap-* namespace but stayed for forensics
    assert list(tmp_path.glob(f"quarantine-{torn_name}"))
    assert not (tmp_path / torn_name).exists()


def test_no_restorable_snapshot_returns_false(tmp_path):
    n = ReplicaNode(rid=0, capacity=32)
    assert not checkpoint.load_latest_node(tmp_path, n)  # empty root
    n.add_command({"x": "1"}, ts=10)
    snap = checkpoint.save_node_atomic(tmp_path, n)
    tear_snapshot(snap)
    n2 = ReplicaNode(rid=0, capacity=32)
    assert not checkpoint.load_latest_node(tmp_path, n2)  # only snap torn
    assert _events(n2, "snapshot_quarantine")


def test_fsync_stall_injection(tmp_path):
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "1"}, ts=10)
    t0 = time.perf_counter()
    with fsync_stall(0.02):
        checkpoint.save_node_atomic(tmp_path, n)
    assert time.perf_counter() - t0 >= 0.02  # >=1 stalled fsync ran
    assert checkpoint._FSYNC_STALL_S == 0.0  # restored on exit


# ---- circuit breaker: half-open transitions + decorrelated jitter ----


def test_circuit_breaker_half_open_transitions():
    now = {"t": 0.0}
    peer = RemotePeer("http://127.0.0.1:9", backoff_base_s=1.0,
                      backoff_cap_s=30.0, rng=random.Random("cb"),
                      clock=lambda: now["t"])
    assert peer.circuit_state() == "closed" and not peer.backed_off()
    peer._note_transport_failure()
    assert peer.circuit_state() == "open" and peer.backed_off()
    assert peer.failures == 1
    assert 1.0 <= peer.retry_at <= 3.0  # first window: U(base, 3*base)

    now["t"] = peer.retry_at + 0.01  # window expired
    assert not peer.backed_off()  # this caller IS the half-open probe
    assert peer.circuit_state() == "half_open"
    assert peer.backed_off()  # everyone else keeps waiting on the probe

    peer._note_transport_failure()  # probe failed: re-open, fresh window
    assert peer.circuit_state() == "open" and peer.backed_off()
    now["t"] = peer.retry_at + 0.01
    assert not peer.backed_off()  # next probe
    peer._note_reachable()  # probe succeeded
    assert peer.circuit_state() == "closed"
    assert peer.failures == 0 and not peer.backed_off()


def test_backoff_jitter_is_decorrelated_and_capped():
    deadlines = set()
    for s in range(6):
        p = RemotePeer("http://127.0.0.1:9", backoff_base_s=0.5,
                       backoff_cap_s=4.0, rng=random.Random(f"j{s}"),
                       clock=lambda: 0.0)
        for _ in range(8):
            p._note_transport_failure()
            assert 0.5 <= p._delay <= 4.0  # jittered, never past the cap
        deadlines.add(p.retry_at)
    # different agents must NOT re-probe a revived peer in lockstep
    assert len(deadlines) > 1


def test_failure_threshold_gates_the_breaker():
    peer = RemotePeer("http://127.0.0.1:9", failure_threshold=3,
                      rng=random.Random("th"), clock=lambda: 0.0)
    peer._note_transport_failure()
    peer._note_transport_failure()
    assert peer.circuit_state() == "closed" and not peer.backed_off()
    peer._note_transport_failure()  # third consecutive failure trips it
    assert peer.circuit_state() == "open" and peer.backed_off()


def test_circuit_state_gauges(served):
    peer = RemotePeer("http://127.0.0.1:9", clock=lambda: 0.0,
                      rng=random.Random("g"))
    peer._note_transport_failure()
    m = Metrics()
    health.sample_peer_circuits(m.registry, "0", [peer])
    assert m.registry.gauge_value("net_peer_circuit_state", node="0",
                                  peer=peer.url) == 2  # open
    assert m.registry.gauge_value("net_peers_unreachable", node="0") == 1
    assert m.registry.gauge_value("net_peers_total", node="0") == 1
    # and the served /metrics endpoint samples its agent's breakers
    import urllib.request

    with urllib.request.urlopen(served.url + "/metrics", timeout=5) as res:
        body = res.read().decode()
    assert "net_peers_total" in body


# ---- wire injectors, pinned one at a time ----


def test_drop_injector_counts_transport_failure(served):
    node, agent = _puller()
    t = FaultyTransport(served.url, _plane(FaultRule("drop")), "0", "1")
    assert not agent.pull_from(t)
    assert node.get_state() == {}
    assert t.failures == 1 and t.circuit_state() == "open"
    assert agent.metrics._counts["net_gossip_skipped"] == 1
    assert [r["fault"] for r in t.plane.log] == ["drop"]


def test_truncate_injector_skips_never_partially_merges(served):
    node, agent = _puller()
    t = FaultyTransport(served.url, _plane(FaultRule("truncate")), "0", "1")
    assert not agent.pull_from(t)
    # a cut body must surface as NO payload — a partial merge would leave
    # a permanent hole under the version vector
    assert node.get_state() == {} and node.version_vector() == {}
    assert agent.metrics._counts["net_gossip_skipped"] == 1
    t.plane.heal()
    assert agent.pull_from(t)  # transport recovers instantly after heal
    assert node.get_state() == served.node.get_state()


def test_corrupt_injector_quarantines_and_loop_survives(served):
    node, agent = _puller()
    t = FaultyTransport(served.url, _plane(FaultRule("corrupt")), "0", "1")
    assert not agent.pull_from(t)  # mangled payload: quarantined, not fatal
    assert node.get_state() == {}
    assert agent.metrics._counts["net_gossip_quarantined"] == 1
    [q] = _events(node, "payload_quarantine")
    assert q["surface"] == "net_gossip" and "ValueError" in q["error"]
    t.plane.heal()
    assert agent.pull_from(t)  # the reference's loop would be dead here
    assert node.get_state() == served.node.get_state()


def test_duplicate_injector_second_delivery_noops(served):
    node, agent = _puller()
    t = FaultyTransport(served.url, _plane(FaultRule("duplicate")), "0", "1")
    assert agent.pull_from(t)  # delivered AND queued for redelivery
    assert t.pending_redelivery() == 1
    state = json.dumps(node.get_state(), sort_keys=True)
    vv = node.version_vector()
    assert not agent.pull_from(t)  # identical bytes again: semantic no-op
    assert t.pending_redelivery() == 0
    assert json.dumps(node.get_state(), sort_keys=True) == state
    assert node.version_vector() == vv


def test_reorder_injector_old_after_new_noops(served):
    node, agent = _puller()
    plane = _plane(FaultRule("reorder", end=1))  # holds step 0 only
    t = FaultyTransport(served.url, plane, "0", "1")
    assert not agent.pull_from(t)  # payload withheld: empty delta
    assert t.pending_redelivery() == 1 and node.get_state() == {}
    plane.step = 1
    served.node.add_command({"z": "7"}, ts=12)  # newer state arrives first
    node.receive(served.node.gossip_payload())
    state = json.dumps(node.get_state(), sort_keys=True)
    vv = node.version_vector()
    assert not agent.pull_from(t)  # held OLD payload lands after: no-op
    assert t.pending_redelivery() == 0
    assert json.dumps(node.get_state(), sort_keys=True) == state
    assert node.version_vector() == vv


def test_delay_injector_sleeps_but_delivers(served):
    node, agent = _puller()
    t = FaultyTransport(
        served.url, _plane(FaultRule("delay", arg=0.01)), "0", "1")
    t0 = time.perf_counter()
    assert agent.pull_from(t)
    assert time.perf_counter() - t0 >= 0.01
    assert node.get_state() == served.node.get_state()


# ---- NetworkAgent-layer duplicate/reorder idempotence (scripted peer) ----


class _ScriptedPeer:
    """Duck-typed RemotePeer: serves a fixed payload sequence."""

    url = "scripted://peer"

    def __init__(self, payloads):
        self.payloads = list(payloads)

    def gossip_payload(self, since=None, trace=None):
        return self.payloads.pop(0) if self.payloads else {}


def test_agent_duplicate_and_reorder_delivery_idempotent():
    src = ReplicaNode(rid=1, capacity=64)
    src.add_command({"a": "1"}, ts=10)
    older = src.gossip_payload()  # pre-update payload
    src.add_command({"b": "2"}, ts=11)
    newer = src.gossip_payload()

    node, agent = _puller()
    # newer twice (duplicate), then older after newer (reorder)
    peer = _ScriptedPeer([newer, newer, older])
    assert agent.pull_from(peer)
    state = json.dumps(node.get_state(), sort_keys=True)
    vv = node.version_vector()
    assert not agent.pull_from(peer)  # duplicate: no-op
    assert not agent.pull_from(peer)  # out-of-order old payload: no-op
    assert json.dumps(node.get_state(), sort_keys=True) == state
    assert node.version_vector() == vv
    assert state == json.dumps(src.get_state(), sort_keys=True)


def test_validate_payload_flags_malformed_bodies(served):
    node = ReplicaNode(rid=0, capacity=32)
    good = served.node.gossip_payload()
    assert node.validate_payload(good) is None
    assert "ValueError" in node.validate_payload(
        {"nemesis:corrupt:key": {"a": "b"}})
    bad_cmd = dict(good)
    wire_key = next(k for k in bad_cmd if not k.startswith("__"))
    bad_cmd[wire_key] = "not-a-dict"
    assert "non-dict command" in node.validate_payload(bad_cmd)


# ---- schedule/plane determinism + disk shim ----


def test_schedule_generation_is_deterministic():
    a = NemesisSchedule.generate(7, 3, 100)
    b = NemesisSchedule.generate(7, 3, 100)
    assert a == b
    assert a != NemesisSchedule.generate(8, 3, 100)
    assert NemesisSchedule.from_json(a.to_json()) == a
    assert a.rules and any(r.kind == "drop" for r in a.rules)


def test_plane_decisions_replay_identically():
    sched = NemesisSchedule.generate(7, 3, 100)
    p1, p2 = FaultPlane(sched), FaultPlane(sched)
    for step in (0, 3, 17, 50):
        p1.step = p2.step = step
        for src, dst in (("0", "1"), ("1", "2"), ("2", "0")):
            assert p1.decide(src, dst, "gossip") == p2.decide(
                src, dst, "gossip")
    p1.heal()
    assert p1.decide("0", "1", "gossip") == {}


def test_fault_log_is_step_indexed_without_wall_time(tmp_path):
    log_path = tmp_path / "faults.jsonl"
    plane = FaultPlane(NemesisSchedule(seed=0, steps=10, nodes=2,
                                       rules=(), skews=()),
                       log_path=str(log_path))
    plane.step = 3
    plane.record("drop", src="0", dst="1", op="gossip")
    plane.heal()
    plane.close()
    recs = [json.loads(line) for line in log_path.read_text().splitlines()]
    assert recs == [
        {"step": 3, "fault": "drop", "src": "0", "dst": "1",
         "op": "gossip"},
        {"step": 3, "fault": "heal"},
    ]


def test_faulty_disk_torn_write_detected_on_restore(tmp_path):
    plane = _plane(FaultRule("truncate", op="disk"))
    disk = FaultyDisk(plane, "0")
    n = ReplicaNode(rid=0, capacity=32)
    n.add_command({"x": "1"}, ts=10)
    snap, torn = disk.save(str(tmp_path), n)
    assert torn
    assert checkpoint.verify_snapshot(snap) is not None
    assert any(r["fault"] == "torn_write" for r in plane.log)
    n2 = ReplicaNode(rid=0, capacity=32)
    assert not checkpoint.load_latest_node(tmp_path, n2)  # only snap torn


# ---- end-to-end smoke ----


def test_nemesis_soak_smoke():
    from crdt_tpu.harness.nemesis_soak import run_soak

    rep = run_soak(seed=0, nodes=2, steps=30)
    assert rep.writes > 0 and rep.final_keys > 0
