"""Ingest front door tests: the columnar op-page wire format's
rejection matrix (decode-validates-everything — a malformed page is
quarantined WHOLE, a truncated page is "no page" never "some ops"), the
round-trip property, the micro-batch admission queue's
one-dispatch-per-drain accounting (the write-side analogue of the
fused-pull pins in tests/test_pipeline.py), the deterministic shed
policy's loud 429 + black-box provenance, and the singleton-vs-batched
parity the shared admission path guarantees."""
from __future__ import annotations

import json
import random
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.ingest import (
    AdmissionQueue,
    IngestFrontDoor,
    PageBuilder,
    PageFormatError,
    ShedError,
    decode_page,
    encode_page,
)
from crdt_tpu.ingest.wire import HEADER_SIZE, MAX_OPS_PER_PAGE, OpPage
from crdt_tpu.utils.clock import HostClock
from crdt_tpu.utils.config import ClusterConfig


def _page(n=8, origin=3, page_seq=0, seed=0) -> OpPage:
    rng = random.Random(seed)
    keys = [f"k{i}" for i in range(max(2, n // 2))]
    values = [str(rng.randrange(1000)) for _ in range(max(2, n // 2))]
    return OpPage(
        origin=origin, page_seq=page_seq,
        seq=np.arange(n, dtype=np.uint32),
        wire_ts=np.asarray([100 + i for i in range(n)], np.int32),
        key_id=np.asarray([rng.randrange(len(keys)) for _ in range(n)],
                          np.uint32),
        val_id=np.asarray([rng.randrange(len(values)) for _ in range(n)],
                          np.uint32),
        keys=keys, values=values,
    )


# ---- wire format: round trip ----


def test_page_round_trip():
    p = _page(n=16, seed=7)
    q = decode_page(encode_page(p))
    assert q.origin == p.origin and q.page_seq == p.page_seq
    for a, b in ((q.seq, p.seq), (q.wire_ts, p.wire_ts),
                 (q.key_id, p.key_id), (q.val_id, p.val_id)):
        assert np.array_equal(a, b)
    assert q.keys == p.keys and q.values == p.values


def test_page_round_trip_property_sweep():
    """Seeded sweep: every generated page survives encode->decode with
    identical planes and rows() materializes the right commands."""
    for seed in range(12):
        rng = random.Random(seed)
        n = rng.randrange(1, 40)
        p = _page(n=n, origin=rng.randrange(100), page_seq=seed, seed=seed)
        q = decode_page(encode_page(p))
        rows = q.rows()
        assert len(rows) == n
        for i, (ts, cmd) in enumerate(rows):
            assert ts == int(p.wire_ts[i])
            assert cmd == {p.keys[int(p.key_id[i])]:
                           p.values[int(p.val_id[i])]}


def test_builder_emits_at_page_size_and_flush():
    b = PageBuilder(origin=5, page_size=3)
    assert b.add("a", "1") is None
    assert b.add("b", "2") is None
    raw = b.add("c", "3")
    assert raw is not None
    page = decode_page(raw)
    assert page.n_ops == 3 and page.page_seq == 0
    assert b.flush() is None  # nothing pending
    b.add("d", "4")
    tail = b.flush()
    assert decode_page(tail).page_seq == 1  # page seqs advance per emit
    # per-origin op seqs keep increasing across pages
    assert int(decode_page(tail).seq[0]) == 3


def test_builder_interns_repeated_keys_once():
    b = PageBuilder(origin=1, page_size=8)
    for _ in range(3):
        b.add("hot", "1")
    b.add("cold", "2")
    page = decode_page(b.flush() or b"")
    assert sorted(page.keys) == ["cold", "hot"]  # interned, not repeated


# ---- wire format: rejection matrix ----


@pytest.mark.parametrize("mutate,why", [
    (lambda raw: b"NOTAPAGE" + raw[8:], "bad magic"),
    (lambda raw: raw[:8] + b"\xff\x00" + raw[10:], "unknown version"),
    (lambda raw: raw[:10] + b"\x01\x00" + raw[12:], "reserved flags"),
    (lambda raw: raw[:12] + (-1).to_bytes(4, "little", signed=True)
        + raw[16:], "negative origin"),
    (lambda raw: raw[:20] + (0).to_bytes(4, "little") + raw[24:],
     "zero ops"),
    (lambda raw: raw[:20] + (MAX_OPS_PER_PAGE + 1).to_bytes(4, "little")
        + raw[24:], "n_ops over cap"),
    (lambda raw: raw[:-1], "truncated tail"),
    (lambda raw: raw + b"\x00", "trailing garbage"),
    (lambda raw: raw[:HEADER_SIZE - 4] + b"\x00\x00\x00\x00"
        + raw[HEADER_SIZE:], "crc mismatch"),
])
def test_malformed_page_rejected(mutate, why):
    raw = encode_page(_page())
    with pytest.raises(PageFormatError):
        decode_page(mutate(raw))


def test_rejects_non_monotone_seq_plane():
    p = _page(n=4)
    p.seq = np.asarray([0, 2, 1, 3], np.uint32)
    with pytest.raises(PageFormatError, match="strictly increasing"):
        decode_page(encode_page(p))


def test_rejects_out_of_window_ts():
    p = _page(n=2)
    p.wire_ts = np.asarray([5, -7], np.int32)  # -7 is not WIRE_TS_NOW
    with pytest.raises(PageFormatError, match="wire-ts"):
        decode_page(encode_page(p))


def test_rejects_out_of_bounds_ids():
    p = _page(n=2)
    p.key_id = np.asarray([0, 99], np.uint32)
    with pytest.raises(PageFormatError, match="key-id"):
        decode_page(encode_page(p))
    p = _page(n=2)
    p.val_id = np.asarray([0, 99], np.uint32)
    with pytest.raises(PageFormatError, match="value-id"):
        decode_page(encode_page(p))


def test_truncation_sweep_never_partially_decodes():
    """FaultyTransport's truncation contract, at the page layer: every
    proper prefix of a valid page is 'no page' — PageFormatError — never
    a page with fewer ops."""
    raw = encode_page(_page(n=8, seed=3))
    for cut in range(len(raw)):
        with pytest.raises(PageFormatError):
            decode_page(raw[:cut])


def test_corruption_fuzz_never_partially_admits():
    """Planted single-byte defects (the nemesis corrupt injector's
    shape): decode either rejects the page whole, or — only when the
    flip lands outside every validated field AND survives crc32, which
    a single-byte flip cannot — yields the original op count.  No
    outcome admits a subset of ops."""
    raw = encode_page(_page(n=8, seed=11))
    rng = random.Random(42)
    for _ in range(200):
        pos = rng.randrange(len(raw))
        flip = bytes([raw[pos] ^ (1 << rng.randrange(8))])
        bad = raw[:pos] + flip + raw[pos + 1:]
        try:
            page = decode_page(bad)
        except PageFormatError:
            continue
        assert page.n_ops == 8  # full page or nothing


# ---- admission queue: one dispatch per drain ----


def test_kv_drain_is_one_dispatch():
    """The acceptance pin: however many ops and submitters a drain
    fuses, it costs exactly ONE merge_dispatches increment — the write-
    side fused_pull_round."""
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=10_000, flush_deadline_s=60.0)
    for i in range(25):
        fd.kv.submit((100 + i, {f"k{i}": str(i)}))
    assert node.metrics.registry.counter_value("merge_dispatches") == 0
    assert fd.kv.flush() == 25
    assert node.metrics.registry.counter_value("merge_dispatches") == 1
    assert len(node.get_state()) == 25
    reg = node.metrics.registry
    assert reg.counter_value("ingest_drains", lane="kv", node="0") == 1
    assert reg.counter_value("ingest_ops_admitted", lane="kv",
                             node="0") == 25
    h = reg.histogram("ingest_batch_size", lane="kv", node="0")
    assert h is not None and h.count == 1


def test_page_plus_singletons_fuse_into_one_drain():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=10_000, flush_deadline_s=60.0)
    b = PageBuilder(origin=9, page_size=4)
    raw = [b.add(f"p{i}", str(i), ts=10 + i) for i in range(4)][-1]
    page_ticket = threading.Thread(target=fd.admit_page, args=(raw,))
    page_ticket.start()
    fd.kv.submit((50, {"solo": "1"}))
    # drain everything pending — page ops and the singleton — at once
    while fd.kv.depth < 5:
        pass  # page thread enqueues asynchronously; tiny spin
    assert fd.kv.flush() == 5
    page_ticket.join()
    assert node.metrics.registry.counter_value("merge_dispatches") == 1
    assert len(node.get_state()) == 5


def test_flush_on_size_triggers_at_max_batch():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=4, flush_deadline_s=60.0)
    for i in range(3):
        fd.kv.submit((i, {f"a{i}": "1"}))
    assert node.metrics.registry.counter_value("merge_dispatches") == 0
    t = fd.kv.submit((3, {"a3": "1"}))  # 4th op: size trigger drains inline
    assert t.done
    assert node.metrics.registry.counter_value("merge_dispatches") == 1
    assert fd.kv.depth == 0


def test_ticket_deadline_flush_unblocks_lone_writer():
    """Cooperative flush-on-deadline: a single submitter on an idle
    queue drains the queue itself after flush_deadline_s — no background
    thread required for liveness."""
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=0.005)
    ident = fd.admit_kv({"x": "9"}, ts=123)
    assert ident == (0, 0)
    assert node.get_state() == {"x": "9"}


def test_concurrent_submitters_share_drains():
    """8 threads x 20 ops against a size-triggered queue: every op lands
    exactly once and the dispatch count is the DRAIN count (<< op
    count), pinned by the drain counter staying equal."""
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=16, flush_deadline_s=0.002)

    def worker(w):
        for i in range(20):
            fd.admit_kv({f"w{w}_{i}": "1"}, ts=w * 100 + i)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fd.flush_all()
    assert len(node.get_state()) == 160
    reg = node.metrics.registry
    dispatches = reg.counter_value("merge_dispatches")
    drains = reg.counter_value("ingest_drains", lane="kv", node="0")
    assert dispatches == drains < 160


def test_drain_preserves_submission_order():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=60.0)
    tickets = [fd.kv.submit((i, {"k": str(i)})) for i in range(10)]
    fd.kv.flush()
    idents = [t.wait(1.0)[0] for t in tickets]
    # seqs mint in submission order: admission ordering stays explicit
    assert [s for _r, s in idents] == list(range(10))
    assert node.get_state() == {"k": "45"}  # counter: all 10 deltas landed


# ---- shed policy ----


def test_shed_is_deterministic_loud_and_total():
    node = ReplicaNode(rid=4)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=60.0,
                         high_water=10, retry_after_s=0.25)
    fd.kv.submit_many([(i, {f"k{i}": "1"}) for i in range(10)])
    with pytest.raises(ShedError) as ei:
        fd.kv.submit((99, {"over": "1"}))
    assert ei.value.retry_after_s == 0.25
    reg = node.metrics.registry
    assert reg.counter_value("ingest_shed", lane="kv", node="4") == 1
    assert reg.counter_value("ingest_shed_ops", lane="kv", node="4") == 1
    # the black box records the shed (never a silent drop)
    sheds = node.events.find(event="ingest_shed")
    assert len(sheds) == 1 and sheds[0]["n_ops"] == 1
    assert sheds[0]["high_water"] == 10
    # after a drain the same submission admits: pure depth threshold
    fd.kv.flush()
    assert fd.kv.submit((99, {"over": "1"})) is not None
    # conservation: everything submitted is either admitted or shed
    fd.flush_all()
    admitted = reg.counter_value("ingest_ops_admitted", lane="kv", node="4")
    shed_ops = reg.counter_value("ingest_shed_ops", lane="kv", node="4")
    assert admitted + shed_ops == 12


def test_page_shed_is_all_or_nothing_and_retryable():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=0.005,
                         high_water=6)
    b = PageBuilder(origin=2, page_size=4)
    raw = [b.add(f"x{i}", "1") for i in range(4)][-1]
    fd.kv.submit_many([(i, {f"fill{i}": "1"}) for i in range(4)])
    with pytest.raises(ShedError):
        fd.admit_page(raw)  # 4 pending + 4 page ops > 6
    assert fd.kv.depth == 4  # nothing from the page entered the queue
    fd.kv.flush()
    out = fd.admit_page(raw)  # same page retries cleanly after the drain
    assert out == {"admitted": 4, "dup": False, "page_seq": 0}
    # and only now does a replay of it dedup
    assert fd.admit_page(raw)["dup"] is True


def test_oversized_page_always_sheds():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, high_water=8)
    b = PageBuilder(origin=1, page_size=16)
    raw = [b.add(f"y{i}", "1") for i in range(16)][-1]
    with pytest.raises(ShedError):
        fd.admit_page(raw)


# ---- singleton/batched parity (the shared code path) ----


def test_add_commands_parity_with_add_command():
    """One batched mint == N singleton mints: same state, same vv, same
    log planes — bit-identical, 1 dispatch vs N."""
    clock = HostClock()
    batched = ReplicaNode(rid=0, clock=clock)
    single = ReplicaNode(rid=0, clock=clock)
    cmds = [{f"k{i}": str(i), "shared": str(i)} for i in range(12)]
    tss = [50 + i for i in range(12)]
    idents = batched.add_commands(cmds, tss)
    for cmd, ts in zip(cmds, tss):
        single.add_command(cmd, ts=ts)
    assert idents == [(0, i) for i in range(12)]
    assert batched.get_state() == single.get_state()
    assert batched.version_vector() == single.version_vector()
    for name in ("ts", "rid", "seq", "key", "val", "payload", "is_num"):
        assert np.array_equal(np.asarray(getattr(batched.log, name)),
                              np.asarray(getattr(single.log, name)))
    assert batched.metrics.registry.counter_value("merge_dispatches") == 1
    assert single.metrics.registry.counter_value("merge_dispatches") == 12


def test_map_upd_many_parity():
    from crdt_tpu.api.mapnode import MapNode

    a, b = MapNode(rid=1), MapNode(rid=1)
    pairs = [("ka", 5), ("kb", -3), ("ka", 2), ("kc", 7)]
    idents_a = a.upd_many(pairs)
    idents_b = [b.upd(k, d) for k, d in pairs]
    assert idents_a == idents_b
    assert a.items() == b.items()
    assert a.gossip_payload() == b.gossip_payload()


def test_composite_upd_many_parity():
    from crdt_tpu.api.compositenode import CompositeNode

    a, b = CompositeNode(rid=1), CompositeNode(rid=1)
    pairs = [("ka", 5), ("kb", -3), ("ka", 2)]
    vals_a = a.upd_many(pairs)
    vals_b = [b.upd(k, d) for k, d in pairs]
    assert vals_a == vals_b == [5, -3, 7]
    assert a.items() == b.items()


def test_page_path_state_identical_to_single_op_path():
    """The bench's bit-identity claim, in miniature: the same write
    stream through op pages and through singleton add_command lands the
    IDENTICAL node state and version vector."""
    clock = HostClock()
    paged = ReplicaNode(rid=0, clock=clock)
    single = ReplicaNode(rid=0, clock=clock)
    fd = IngestFrontDoor(paged, max_batch=10_000, flush_deadline_s=0.005)
    b = PageBuilder(origin=1, page_size=8)
    writes = [(f"k{i % 5}", str(i), 100 + i) for i in range(24)]
    for k, v, ts in writes:
        raw = b.add(k, v, ts=ts)
        if raw is not None:
            fd.admit_page(raw)
    tail = b.flush()
    if tail is not None:
        fd.admit_page(tail)
    for k, v, ts in writes:
        single.add_command({k: v}, ts=ts)
    assert paged.get_state() == single.get_state()
    assert paged.version_vector() == single.version_vector()
    for name in ("ts", "rid", "seq", "key", "val", "payload", "is_num"):
        assert np.array_equal(np.asarray(getattr(paged.log, name)),
                              np.asarray(getattr(single.log, name)))
    # 24 ops cost 3 page drains, not 24 dispatches
    assert paged.metrics.registry.counter_value("merge_dispatches") == 3


def test_page_path_gossip_payload_identical_to_single_op_path():
    """The write-behind wire cache must be invisible to gossip readers:
    after paged writes, the SERVED payload (dict form and, when the
    native runtime is in, the direct-to-JSON form) matches a singleton
    twin byte for byte, full dump and delta alike — and a third replica
    that pulls from the paged node converges to the twin's state."""
    import json

    clock = HostClock()
    paged = ReplicaNode(rid=0, clock=clock)
    single = ReplicaNode(rid=0, clock=clock)
    fd = IngestFrontDoor(paged, max_batch=10_000, flush_deadline_s=0.005)
    b = PageBuilder(origin=1, page_size=16)
    writes = [(f"k{i % 5}", str(i - 7), 100 + i) for i in range(64)]
    for k, v, ts in writes:
        raw = b.add(k, v, ts=ts)
        if raw is not None:
            fd.admit_page(raw)
    tail = b.flush()
    if tail is not None:
        fd.admit_page(tail)
    for k, v, ts in writes:
        single.add_command({k: v}, ts=ts)
    for since in (None, {}, {0: 30}, {0: 63}, {7: 3}):
        assert paged.gossip_payload(since) == single.gossip_payload(since)
    fp = getattr(paged, "gossip_payload_json", None)
    fs = getattr(single, "gossip_payload_json", None)
    if fp is not None and fs is not None:  # native runtime present
        for since in (None, {0: 30}):
            jp, js = fp(since), fs(since)
            if isinstance(jp, (str, bytes)):
                jp, js = json.loads(jp), json.loads(js)
            assert jp == js
    receiver = ReplicaNode(rid=9, clock=clock)
    receiver.receive(paged.gossip_payload(None))
    assert receiver.get_state() == single.get_state()


# ---- queue mechanics ----


def test_flush_fn_error_propagates_to_every_ticket():
    boom = RuntimeError("drain died")

    def bad_flush(items):
        raise boom

    q = AdmissionQueue("kv", bad_flush, max_batch=100,
                       flush_deadline_s=60.0)
    t1 = q.submit("a")
    t2 = q.submit("b")
    q.flush()
    for t in (t1, t2):
        with pytest.raises(RuntimeError, match="drain died"):
            t.wait(1.0)
    assert q.metrics.registry.counter_value(
        "ingest_drain_errors", lane="kv", node="?") == 1
    assert q.depth == 0  # the queue survives a failed drain


def test_down_node_fails_drain_whole():
    node = ReplicaNode(rid=0)
    node.set_alive(False)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=0.005)
    assert fd.admit_kv({"x": "1"}) is None  # 502 semantics, not a crash
    node.set_alive(True)
    assert fd.admit_kv({"x": "1"}) is not None


def test_flush_expired_only_past_deadline():
    node = ReplicaNode(rid=0)
    fd = IngestFrontDoor(node, max_batch=1000, flush_deadline_s=30.0)
    fd.kv.submit((1, {"a": "1"}))
    assert fd.kv.flush_expired() == 0  # young group: not drained
    assert fd.kv.depth == 1
    import time as _t
    assert fd.kv.flush_expired(now=_t.monotonic() + 31.0) == 1


# ---- HTTP surface (NodeHost end to end) ----


@pytest.fixture
def served_host():
    from crdt_tpu.api.net import NodeHost

    cfg = ClusterConfig(ingest_flush_ops=8, ingest_flush_ms=2.0,
                        ingest_high_water=16)
    h = NodeHost(rid=0, peers=[], config=cfg)
    t = threading.Thread(target=h._server.serve_forever, daemon=True)
    t.start()
    yield h
    h._server.shutdown()
    h._server.server_close()


def _post(url, path, body, raw=None):
    req = urllib.request.Request(
        url + path,
        data=raw if raw is not None else json.dumps(body).encode(),
        method="POST")
    with urllib.request.urlopen(req, timeout=5.0) as res:
        return res.status, json.loads(res.read() or b"{}") \
            if res.headers.get("Content-Type", "").startswith(
                "application/json") else res.read().decode()


def test_http_page_round_trip_and_dup(served_host):
    from crdt_tpu.api.net import RemotePeer

    p = RemotePeer(served_host.url)
    b = PageBuilder(origin=7, page_size=4)
    raw = [b.add(f"h{i}", str(i)) for i in range(4)][-1]
    assert p.post_page(raw) == {"ok": True, "admitted": 4, "dup": False}
    assert p.post_page(raw)["dup"] is True
    assert p.get_state() == {f"h{i}": str(i) for i in range(4)}


def test_http_oversized_page_429_with_retry_after(served_host):
    b = PageBuilder(origin=7, page_size=32)
    raw = [b.add(f"o{i}", "1") for i in range(32)][-1]  # 32 > high_water 16
    req = urllib.request.Request(served_host.url + "/ingest/page",
                                 data=raw, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5.0)
    assert ei.value.code == 429
    assert float(ei.value.headers["Retry-After"]) > 0
    reg = served_host.node.metrics.registry
    assert reg.counter_value("ingest_shed", lane="kv", node="0") == 1
    # RemotePeer surfaces the same verdict structurally
    from crdt_tpu.api.net import RemotePeer
    out = RemotePeer(served_host.url).post_page(raw)
    assert out["shed"] is True and out["retry_after"] > 0


def test_http_corrupt_page_400_and_quarantine_counter(served_host):
    b = PageBuilder(origin=7, page_size=2)
    raw = [b.add(f"c{i}", "1") for i in range(2)][-1]
    req = urllib.request.Request(served_host.url + "/ingest/page",
                                 data=raw[: len(raw) // 2], method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5.0)
    assert ei.value.code == 400
    reg = served_host.node.metrics.registry
    assert reg.counter_value("ingest_pages_quarantined", node="0") == 1
    assert served_host.node.get_state() == {}  # nothing admitted
    assert len(served_host.node.events.find(
        event="ingest_page_quarantine")) == 1


def test_http_map_and_composite_upd_ride_admission(served_host):
    code, out = _post(served_host.url, "/map/upd", {"key": "m", "delta": 4})
    assert code == 200 and out["rid"] == 0
    code, out = _post(served_host.url, "/composite/upd",
                      {"key": "c", "delta": 2})
    assert code == 200 and out["value"] == 2
    reg = served_host.node.metrics.registry
    assert reg.counter_value("ingest_drains", lane="map", node="0") == 1
    assert reg.counter_value("ingest_drains", lane="composite",
                             node="0") == 1


def test_http_metrics_exposes_ingest_series(served_host):
    from crdt_tpu.api.net import RemotePeer

    p = RemotePeer(served_host.url)
    assert p.add_command({"x": "1"})
    body = urllib.request.urlopen(served_host.url + "/metrics",
                                  timeout=5.0).read().decode()
    for series in ("crdt_ingest_queue_depth", "crdt_ingest_high_water",
                   "crdt_ingest_ops_admitted_total",
                   "crdt_ingest_batch_size", "crdt_ingest_admit_latency"):
        assert series in body, series


def test_http_data_route_shares_admission(served_host):
    """The singleton /data route rides the kv lane: its op shows up in
    the admission accounting, not just the page path's."""
    from crdt_tpu.api.net import RemotePeer

    assert RemotePeer(served_host.url).add_command({"d": "1"})
    reg = served_host.node.metrics.registry
    assert reg.counter_value("ingest_ops_admitted", lane="kv",
                             node="0") == 1
    assert served_host.node.get_state() == {"d": "1"}
