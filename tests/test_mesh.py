"""Mesh-sharded anti-entropy tests on the 8-virtual-device CPU mesh
(conftest.py sets xla_force_host_platform_device_count=8): the explicit
collective paths (pmax, recursive-doubling ppermute join) must agree with
the single-device reference implementations."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.models import gcounter, oplog
from crdt_tpu.parallel import mesh as mesh_lib
from crdt_tpu.parallel.compat import shard_map
from crdt_tpu.parallel import swarm
from tests import helpers
from tests.helpers import tree_equal


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return mesh_lib.make_mesh(8)


def _counter_swarm(rng, r, n_nodes=8):
    counts = np.asarray(rng.integers(0, 100, (r, n_nodes)), np.int32)
    return swarm.make(gcounter.GCounter(counts=jnp.asarray(counts)))


def test_pmax_converge_matches_local(mesh8):
    rng = np.random.default_rng(0)
    s = _counter_swarm(rng, r=64)
    expect = swarm.converge(s, gcounter.join, gcounter.zero(8))

    sharded = mesh_lib.shard_swarm(s, mesh8)
    step = mesh_lib.pmax_converge(mesh8)
    got = step(sharded)
    assert tree_equal(jax.device_get(got.state), jax.device_get(expect.state))


def test_pmax_converge_respects_alive_mask(mesh8):
    rng = np.random.default_rng(1)
    s = _counter_swarm(rng, r=32)
    s = swarm.set_alive(s, 5, False)
    s = swarm.set_alive(s, 17, False)
    expect = swarm.converge(s, gcounter.join, gcounter.zero(8))

    got = mesh_lib.pmax_converge(mesh8)(mesh_lib.shard_swarm(s, mesh8))
    assert tree_equal(jax.device_get(got.state), jax.device_get(expect.state))


def test_sharded_converge_generic_join_oplog(mesh8):
    rng = np.random.default_rng(2)
    r, cap = 16, 64
    logs = helpers.rand_oplog_family(rng, n_logs=r, capacity=cap, pool=30, take=10)
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *logs)
    s = swarm.make(state)
    neutral = oplog.empty(cap)
    expect = swarm.converge(s, jax.vmap(oplog.merge), neutral)

    step = mesh_lib.sharded_converge(
        mesh8,
        join_batched=jax.vmap(oplog.merge),
        join_single=oplog.merge,
        neutral=neutral,
    )
    got = step(mesh_lib.shard_swarm(s, mesh8))
    assert tree_equal(jax.device_get(got.state), jax.device_get(expect.state))
    # converged log on every replica = union of all ops
    sizes = np.asarray(jax.vmap(oplog.size)(got.state))
    assert (sizes == sizes[0]).all()


@pytest.mark.parametrize("n_dev", [8, 6])
def test_allreduce_join_both_paths(n_dev):
    """n_dev=8 exercises the recursive-doubling ppermute butterfly; n_dev=6
    (non-power-of-two) exercises the all_gather + tree-reduce fallback."""
    rng = np.random.default_rng(3)
    cap = 32
    logs = helpers.rand_oplog_family(rng, n_logs=n_dev, capacity=cap, pool=20, take=8)
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *logs)

    m = mesh_lib.make_mesh(n_dev)
    from jax.sharding import PartitionSpec as P

    def body(x):
        single = jax.tree.map(lambda l: l[0], x)
        out = mesh_lib.allreduce_join(
            oplog.merge, single, "replica", n_dev, neutral=oplog.empty(cap)
        )
        return jax.tree.map(lambda l: l[None], out)

    got = jax.jit(
        shard_map(body, mesh=m, in_specs=P("replica"), out_specs=P("replica"))
    )(state)

    expect = logs[0]
    for l in logs[1:]:
        expect = oplog.merge(expect, l)
    for i in range(n_dev):
        assert tree_equal(jax.tree.map(lambda x, _i=i: x[_i], jax.device_get(got)), jax.device_get(expect))


def test_pjit_auto_sharding_gossip_round(mesh8):
    """The pjit story: jit the plain gossip round over sharded inputs and let
    XLA insert the cross-device gathers — no shard_map needed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(4)
    s = _counter_swarm(rng, r=64)
    sharded = mesh_lib.shard_swarm(s, mesh8)
    peers = swarm.random_peers(jax.random.key(0), 64)
    peers = jax.device_put(peers, NamedSharding(mesh8, P("replica")))

    step = jax.jit(lambda sw, p: swarm.gossip_round(sw, p, gcounter.join))
    got = step(sharded, peers)
    expect = swarm.gossip_round(s, peers, gcounter.join)
    assert tree_equal(jax.device_get(got.state), jax.device_get(expect.state))
