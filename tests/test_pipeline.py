"""Pipelined merge runtime tests: k-way fused pull rounds, the dispatch
count they are bought with, per-peer transport backoff, and the
double-buffered stripe executor's determinism.

The fused paths are only legal because the op-log union is ACI
(tests/test_lattice_laws.py pins the laws on the lattice itself); here we
pin the RUNTIME consequence: merging P payloads in one dispatch is
bit-exact against P sequential merges in any payload order, and costs
exactly one ``merge_dispatches`` increment (the acceptance assertion —
``crdt_merge_dispatches_total`` on /metrics)."""
from __future__ import annotations

import threading

import numpy as np
import pytest

from crdt_tpu.api.cluster import LocalCluster
from crdt_tpu.api.node import ReplicaNode
from crdt_tpu.utils.clock import HostClock
from crdt_tpu.utils.config import ClusterConfig


def _writers(n=3, ops_per=4):
    """n writer nodes with disjoint rids, some overlapping keys."""
    ws = [ReplicaNode(rid=1 + i) for i in range(n)]
    for i, w in enumerate(ws):
        for j in range(ops_per):
            # every writer touches k_shared: the fused batch carries
            # cross-payload key collisions, not just disjoint rows
            w.add_command({f"k{i}_{j}": str(10 * i + j), "k_shared": str(j)})
    return ws


def _log_planes(node):
    log = node.log
    return [np.asarray(x) for x in
            (log.ts, log.rid, log.seq, log.key, log.val, log.payload,
             log.is_num)]


def test_receive_many_bit_exact_vs_sequential():
    """ONE fused merge of P payloads == P sequential receives: same state,
    same version vector, same fresh-op count, and (same payload order +
    shared clock ⇒ same interner assignments) bit-identical log planes."""
    payloads = [w.gossip_payload(since=None) for w in _writers()]
    clock = HostClock()
    fused = ReplicaNode(rid=0, clock=clock)
    seq = ReplicaNode(rid=0, clock=clock)

    fresh_fused = fused.receive_many(payloads)
    fresh_seq = sum(seq.receive(p) for p in payloads)

    assert fresh_fused == fresh_seq > 0
    assert fused.get_state() == seq.get_state()
    assert fused.version_vector() == seq.version_vector()
    for a, b in zip(_log_planes(fused), _log_planes(seq)):
        assert np.array_equal(a, b)


def test_receive_many_order_insensitive():
    """Payload order changes interner internals, never the observable
    state (union-ACI): permuted and duplicated payload lists land on the
    same state, vv, and fresh count (in-batch dedup == re-delivery dedup)."""
    payloads = [w.gossip_payload(since=None) for w in _writers()]
    a = ReplicaNode(rid=0)
    b = ReplicaNode(rid=0)
    fresh_a = a.receive_many(payloads)
    # reversed AND one payload re-delivered inside the same fused batch
    fresh_b = b.receive_many(list(reversed(payloads)) + [payloads[0]])
    assert fresh_a == fresh_b
    assert a.get_state() == b.get_state()
    assert a.version_vector() == b.version_vector()


def test_fused_round_costs_one_dispatch():
    """The acceptance assertion: a P-peer fused round costs ONE ingest
    dispatch (sequential costs P), pinned by the merge_dispatches counter
    that /metrics exposes as crdt_merge_dispatches_total."""
    payloads = [w.gossip_payload(since=None) for w in _writers()]
    fused = ReplicaNode(rid=0)
    seq = ReplicaNode(rid=0)
    fused.receive_many(payloads)
    for p in payloads:
        seq.receive(p)
    assert fused.metrics.registry.counter_value("merge_dispatches") == 1
    assert seq.metrics.registry.counter_value("merge_dispatches") == len(
        payloads)
    # and the exposition carries it under the wire name the assertion
    # (and any scraper) uses
    assert "crdt_merge_dispatches_total" in \
        fused.metrics.registry.render_prometheus()


def test_cluster_fused_round_dispatch_budget():
    """One k=3 LocalCluster pull round stays within the <=2 dispatch
    acceptance budget (it is exactly 1 when anything merges) and records
    the fused fan-in."""
    c = LocalCluster(ClusterConfig(n_replicas=4, fuse_pull_k=3, seed=3))
    for i, n in enumerate(c.nodes):
        n.add_command({f"k{i}": str(i)})
    reg = c.metrics.registry
    before = reg.counter_value("merge_dispatches")
    assert c.gossip_once(0)
    after = reg.counter_value("merge_dispatches")
    assert after - before == 1  # <= 2 required; fused round needs just 1
    assert reg.counter_value("pull_round_peers_fused", node="0") == 3


def test_fused_cluster_converges_like_sequential():
    """Same writes through a k=3 fused cluster and a k=1 sequential one:
    both reach the identical fixpoint (numeric folds are order-free)."""
    cf = LocalCluster(ClusterConfig(n_replicas=4, fuse_pull_k=3, seed=11))
    cs = LocalCluster(ClusterConfig(n_replicas=4, seed=11))
    for c in (cf, cs):
        for i, n in enumerate(c.nodes):
            n.add_command({f"k{i}": str(2 * i - 3), "shared": "5"})
    for _ in range(12):
        cf.tick()
        cs.tick()
    assert cf.converged() and cs.converged()
    assert cf.nodes[0].get_state() == cs.nodes[0].get_state()
    # fused convergence used strictly fewer ingest dispatches
    assert (cf.metrics.registry.counter_value("merge_dispatches")
            < cs.metrics.registry.counter_value("merge_dispatches"))


# ---- network layer (real sockets, test_net.py harness style) ----


@pytest.fixture
def trio():
    """Three served NodeHosts; host a pulls k=2-fused from b and c."""
    from crdt_tpu.api.net import NodeHost, RemotePeer

    cfg = ClusterConfig(fuse_pull_k=2)
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[])
    c = NodeHost(rid=2, peers=[])
    a.agent.peers = [RemotePeer(b.url), RemotePeer(c.url)]
    for h in (a, b, c):
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()
    yield a, b, c
    for h in (a, b, c):
        h._server.shutdown()
        h._server.server_close()


def test_network_fused_round(trio):
    from crdt_tpu.api.net import RemotePeer

    a, b, c = trio
    RemotePeer(b.url).add_command({"x": "5"})
    RemotePeer(c.url).add_command({"y": "7"})
    reg = a.node.metrics.registry
    assert a.agent.gossip_once()  # ONE round fuses both peers' payloads
    assert a.node.get_state() == {"x": "5", "y": "7"}
    assert reg.counter_value("merge_dispatches") == 1
    assert reg.counter_value("pull_round_peers_fused", node="0") == 2


def test_network_fused_dead_peer_counts_skip(trio):
    from crdt_tpu.api.net import RemotePeer

    a, b, c = trio
    c.node.set_alive(False)  # reachable-but-down: served 502s
    RemotePeer(b.url).add_command({"x": "1"})
    before = a.agent.metrics.snapshot().get("net_gossip_skipped", 0)
    assert a.agent.gossip_once()  # b's payload still merges
    assert a.node.get_state() == {"x": "1"}
    assert a.agent.metrics.snapshot()["net_gossip_skipped"] == before + 1
    # a served 502 is NOT a transport failure: no backoff, and the revived
    # peer is pulled again on the very next fused round
    assert not any(p.backed_off() for p in a.agent.peers)
    c.node.set_alive(True)
    RemotePeer(c.url).add_command({"y": "2"})
    for _ in range(6):  # k=2 always samples both available peers
        a.agent.gossip_once()
    assert a.node.get_state() == {"x": "1", "y": "2"}


def test_transport_backoff_skips_unreachable_peer():
    """A connection-refused peer backs off exponentially and is skipped
    LOUDLY (net_peer_backoff_skips) while a live peer keeps merging; a
    served-502 peer never backs off (revival must be picked up on the
    next round — the dead/revive semantics tests/test_net.py pins)."""
    from crdt_tpu.api.net import NodeHost, RemotePeer

    live = NodeHost(rid=1, peers=[])
    t = threading.Thread(target=live._server.serve_forever, daemon=True)
    t.start()
    try:
        cfg = ClusterConfig(peer_backoff_base_s=30.0)
        puller = NodeHost(rid=0, peers=[], config=cfg)
        puller.agent.peers = [
            RemotePeer("http://127.0.0.1:1", backoff_base_s=30.0),
            RemotePeer(live.url),
        ]
        RemotePeer(live.url).add_command({"k": "9"})
        dead, alive_peer = puller.agent.peers
        # first contact pays the connect failure and opens the window
        assert dead.gossip_payload(None) is None
        assert dead.backed_off() and dead.failures == 1
        # every subsequent round routes around it, loudly
        merged = False
        for _ in range(4):
            merged |= puller.agent.gossip_once()
        assert merged and puller.node.get_state() == {"k": "9"}
        skips = puller.agent.metrics.snapshot()["net_peer_backoff_skips"]
        assert skips >= 4
        assert not alive_peer.backed_off()
        puller._server.server_close()
    finally:
        live._server.shutdown()
        live._server.server_close()


# ---- double-buffered stripe executor ----


def test_run_striped_pipelined_matches_serial():
    """Pipelining reorders HOST work only: identical stripe operands ⇒
    bit-identical outputs, and both schedules count one dispatch per
    stripe."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.obs.registry import MetricsRegistry
    from crdt_tpu.parallel import pipeline

    @jax.jit
    def join(a, b):
        return jnp.maximum(a, b)

    def make_build(seed):
        rng = np.random.default_rng(seed)

        def build(i):
            a = rng.integers(0, 1 << 20, size=256).astype(np.int32)
            b = rng.integers(0, 1 << 20, size=256).astype(np.int32)
            return jax.device_put(a), jax.device_put(b)

        return build

    def dispatch(i, a, b):
        return join(a, b)

    reg = MetricsRegistry()
    out_p, stats_p = pipeline.run_striped(
        6, make_build(42), dispatch, pipelined=True, registry=reg,
        pipeline="test")
    out_s, stats_s = pipeline.run_striped(
        6, make_build(42), dispatch, pipelined=False)
    assert len(out_p) == len(out_s) == 6
    for a, b in zip(out_p, out_s):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert stats_p["dispatches"] == stats_s["dispatches"] == 6
    assert 0.0 <= stats_p["occupancy"] <= 1.0
    assert stats_s["occupancy"] == 0.0  # serial arm: no overlapped staging
    # the run is visible on the registry the /metrics surface renders
    assert reg.gauge_value("pipeline_occupancy", pipeline="test") is not None
    assert reg.counter_value("pipeline_stripes", pipeline="test") == 6
    assert reg.counter_value("pipeline_dispatches", pipeline="test") == 6


def test_dispatch_queue_bounded_window():
    """DispatchQueue blocks the oldest dispatch once more than ``depth``
    are in flight, and drain() returns everything in submission order."""
    from crdt_tpu.parallel.pipeline import DispatchQueue

    q = DispatchQueue(depth=1)
    seen = []
    for i in range(5):
        q.submit(lambda x=i: seen.append(x) or x)
        assert len(q._in_flight) <= 1
    assert q.drain() == [0, 1, 2, 3, 4]
    assert q.dispatches == 5
    assert q.drain() == []  # queue resets
