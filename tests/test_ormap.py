"""OR-Map tests (crdt_tpu.models.ormap): observed-remove key semantics
composed with PN-Counter and LWW value lattices, join laws on reachable
states, swarm integration."""
import zlib

import jax
import numpy as np
import pytest

from crdt_tpu.models import lww, ormap, pncounter
from tests.helpers import tree_equal

K, W, NODES = 6, 4, 4
N_TRIALS = 15

pn_join = ormap.joiner(pncounter.join)


def _empty():
    return ormap.empty(K, W, pncounter.zero(NODES))


_next_writer = iter(range(10_000))


def _rand_map(rng: np.random.Generator) -> ormap.ORMap:
    m = _empty()
    w = next(_next_writer) % W  # one writer per generated state, unique mod W
    for _ in range(rng.integers(0, 8)):
        k = int(rng.integers(0, K))
        if rng.random() < 0.25:
            m = ormap.remove(m, k, w)
        else:
            delta = int(rng.integers(-10, 10))
            m = ormap.update(
                m, k, w, lambda v: pncounter.add(v, w % NODES, delta)
            )
    return m


def test_join_laws():
    rng = np.random.default_rng(zlib.crc32(b"ormap"))
    for _ in range(N_TRIALS):
        a, b, c = _rand_map(rng), _rand_map(rng), _rand_map(rng)
        assert tree_equal(pn_join(a, b), pn_join(b, a)), "commutativity"
        assert tree_equal(
            pn_join(pn_join(a, b), c), pn_join(a, pn_join(b, c))
        ), "associativity"
        assert tree_equal(pn_join(a, a), a), "idempotence"
        assert tree_equal(pn_join(a, _empty()), a), "identity"


def test_update_then_read():
    m = _empty()
    m = ormap.update(m, 2, 0, lambda v: pncounter.add(v, 0, 5))
    m = ormap.update(m, 2, 1, lambda v: pncounter.add(v, 1, -3))
    present = np.asarray(ormap.contains(m))
    assert present[2] and not present[0]
    assert int(pncounter.value(ormap.get(m, 2))) == 2


def test_observed_remove_add_wins():
    """A remove masks only what it saw: concurrent update keeps the key."""
    base = ormap.update(_empty(), 1, 0, lambda v: pncounter.add(v, 0, 7))
    a = ormap.remove(base, 1, 1)                   # saw the update, removes
    b = ormap.update(base, 1, 2,
                     lambda v: pncounter.add(v, 2, 1))  # concurrent update
    m = pn_join(a, b)
    assert bool(ormap.contains(m)[1])              # add-wins
    assert int(pncounter.value(ormap.get(m, 1))) == 8
    # sequential remove AFTER seeing everything does hide the key
    m2 = ormap.remove(m, 1, 1)
    assert not bool(ormap.contains(m2)[1])


def test_removed_key_value_accumulates():
    """Documented semantics: value state survives removal (monotone); a
    re-add surfaces the accumulated value."""
    m = ormap.update(_empty(), 3, 0, lambda v: pncounter.add(v, 0, 10))
    m = ormap.remove(m, 3, 0)
    assert not bool(ormap.contains(m)[3])
    m = ormap.update(m, 3, 0, lambda v: pncounter.add(v, 0, 1))
    assert bool(ormap.contains(m)[3])
    assert int(pncounter.value(ormap.get(m, 3))) == 11


def test_lww_valued_map():
    lw_join = ormap.joiner(lww.join)
    m = ormap.empty(K, W, lww.zero())
    m = ormap.update(m, 0, 1, lambda v: lww.write(v, ts=10, rid=1, payload=111))
    n = ormap.empty(K, W, lww.zero())
    n = ormap.update(n, 0, 2, lambda v: lww.write(v, ts=11, rid=2, payload=222))
    j = lw_join(m, n)
    assert int(ormap.get(j, 0).payload) == 222  # newest-timestamp wins
    assert bool(ormap.contains(j)[0])


def test_swarm_converge():
    from crdt_tpu.parallel import swarm

    R = 4
    rows = []
    for r in range(R):
        m = _empty()
        if r == 2:
            m = ormap.update(m, 0, r, lambda v, _r=r: pncounter.add(v, _r, _r + 1))
        rows.append(m)
    state = jax.tree.map(lambda *xs: jax.numpy.stack(xs), *rows)
    s = swarm.make(state)
    s = swarm.converge(s, jax.vmap(pn_join), _empty())
    for i in range(R):
        row = jax.tree.map(lambda x, _i=i: x[_i], s.state)
        assert bool(ormap.contains(row)[0])
        assert int(pncounter.value(ormap.get(row, 0))) == 3


def test_orset_valued_map_composes():
    """The map composes ANY value lattice — including the sorted-table
    OR-Set: per-key element sets with observed-remove keys on top."""
    from crdt_tpu.models import orset

    or_join = ormap.joiner(jax.vmap(orset.join))
    zero = orset.empty(8)
    a = ormap.empty(K, W, zero)
    b = ormap.empty(K, W, zero)
    # writer 0 adds {5, 6} under key 2 on a; writer 1 adds {6, 7} on b
    a = ormap.update(a, 2, 0, lambda s: orset.add(orset.add(s, 5, 0, 0), 6, 0, 1))
    b = ormap.update(b, 2, 1, lambda s: orset.add(orset.add(s, 6, 1, 0), 7, 1, 1))
    m1 = or_join(a, b)
    m2 = or_join(b, a)
    assert tree_equal(m1, m2)
    assert bool(ormap.contains(m1)[2])
    members = np.nonzero(np.asarray(orset.member_mask(ormap.get(m1, 2), 10)))[0]
    assert members.tolist() == [5, 6, 7]
    # remove the KEY on a (observed-remove): b's concurrent update survives
    a2 = ormap.remove(m1, 2, 0)
    b2 = ormap.update(m1, 2, 1, lambda s: orset.add(s, 9, 1, 2))
    m3 = or_join(a2, b2)
    assert bool(ormap.contains(m3)[2]), "concurrent update keeps key alive"
    # and removing an ELEMENT inside the value set tombstones it
    m4 = ormap.update(m3, 2, 1, lambda s: orset.remove(s, 6))
    members = np.nonzero(np.asarray(orset.member_mask(ormap.get(m4, 2), 10)))[0]
    assert 6 not in members.tolist() and 9 in members.tolist()
