"""Cross-process networking tests (crdt_tpu.api.net): replicas in separate
"processes" (separate interner tables, separate epochs, only HTTP between
them) converging over the reference wire surface.

The reference's multi-replica story is one process + loopback HTTP
(/root/reference/main.go:316-323); NodeHost is the same surface as an actual
network daemon, so these tests stand in for true multi-process deployment
(socket transport is identical; process isolation only removes shared
memory, which the string wire format already never uses)."""
from __future__ import annotations

import pytest

from crdt_tpu.api.net import NetworkAgent, NodeHost, RemotePeer
from crdt_tpu.utils.config import ClusterConfig


@pytest.fixture
def pair():
    """Two standalone NodeHosts with disjoint writer ids, peered."""
    a = NodeHost(rid=0, peers=[])
    b = NodeHost(rid=1, peers=[])
    a.agent.peers = [RemotePeer(b.url)]
    b.agent.peers = [RemotePeer(a.url)]
    # serve only (agents driven manually for determinism)
    import threading

    for h in (a, b):
        t = threading.Thread(target=h._server.serve_forever, daemon=True)
        t.start()
    yield a, b
    for h in (a, b):
        h._server.shutdown()
        h._server.server_close()


def test_remote_peer_surface(pair):
    a, b = pair
    ra = RemotePeer(a.url)
    assert ra.ping()
    assert ra.add_command({"x": "5"})
    assert ra.get_state() == {"x": "5"}
    # failure injection round-trips (the reference's broken /condition fixed)
    assert ra.set_alive(False)
    assert not ra.ping() and ra.get_state() is None
    assert ra.set_alive(True)
    assert ra.ping()


def test_two_daemon_convergence(pair):
    a, b = pair
    RemotePeer(a.url).add_command({"x": "5"})
    RemotePeer(b.url).add_command({"x": "-20"})
    RemotePeer(b.url).add_command({"y": "hello"})
    # one pull each direction converges both (delta gossip over real sockets)
    assert a.agent.gossip_once()
    assert b.agent.gossip_once()
    assert a.node.get_state() == b.node.get_state() == {"x": "-15", "y": "hello"}
    # idempotent: re-pull is a no-op (payload empty or all re-deliveries)
    assert not a.agent.gossip_once()


def test_dead_peer_skipped(pair):
    a, b = pair
    b.node.set_alive(False)
    assert not a.agent.gossip_once()  # 502 path: skipped, no exception
    b.node.set_alive(True)
    RemotePeer(b.url).add_command({"k": "1"})
    assert a.agent.gossip_once()
    assert a.node.get_state() == {"k": "1"}


def test_unreachable_peer_skipped():
    n = NodeHost(rid=9, peers=["http://127.0.0.1:1"])  # nothing listens
    assert not n.agent.gossip_once()
    n._server.server_close()


def test_cross_cluster_bridge():
    """Two LocalClusters (disjoint rid ranges, separate interners/epochs)
    bridged by one NetworkAgent each over real HTTP — a two-datacenter
    deployment in miniature."""
    from crdt_tpu.api.cluster import LocalCluster
    from crdt_tpu.api.http_shim import HttpCluster

    ca = LocalCluster(ClusterConfig(n_replicas=2, rid_base=0))
    cb = LocalCluster(ClusterConfig(n_replicas=2, rid_base=2))
    ha, hb = HttpCluster(ca), HttpCluster(cb)
    pa, pb = ha.start(), hb.start()
    try:
        ca.nodes[1].add_command({"a": "10"})
        cb.nodes[1].add_command({"a": "-4"})
        cb.nodes[0].add_command({"b": "world"})
        # intra-cluster convergence first
        for _ in range(8):
            ca.tick()
            cb.tick()
        # bridge: node a0 pulls from b0's port and vice versa
        bridge_a = NetworkAgent(
            ca.nodes[0], [f"http://127.0.0.1:{pb[0]}"], ca.config
        )
        bridge_b = NetworkAgent(
            cb.nodes[0], [f"http://127.0.0.1:{pa[0]}"], cb.config
        )
        assert bridge_a.gossip_once()
        assert bridge_b.gossip_once()
        # spread internally
        for _ in range(8):
            ca.tick()
            cb.tick()
        want = {"a": "6", "b": "world"}
        for n in (*ca.nodes, *cb.nodes):
            assert n.get_state() == want
    finally:
        ha.stop()
        hb.stop()


def test_nodehost_background_loop():
    """Live mode: agents + servers running, convergence happens by itself."""
    cfg = ClusterConfig(gossip_period_ms=30)
    a = NodeHost(rid=0, peers=[], config=cfg)
    b = NodeHost(rid=1, peers=[a.url], config=cfg)
    a.agent.peers = [RemotePeer(b.url)]
    a.start()
    b.start()
    try:
        RemotePeer(a.url).add_command({"x": "1"})
        RemotePeer(b.url).add_command({"x": "2"})
        import time

        deadline = time.time() + 10
        while time.time() < deadline:
            if (
                a.node.get_state() == b.node.get_state() == {"x": "3"}
            ):
                break
            time.sleep(0.05)
        assert a.node.get_state() == b.node.get_state() == {"x": "3"}
    finally:
        a.stop()
        b.stop()
