"""rseq_engine: the GC-aware columnar RSeq engine must be bit-identical
to the generic tomb_gc path (pairwise joins AND gc_round barriers), and
ineligible layouts must fall back loudly — the oplog_engine contract,
instantiated for the sequence lattice (VERDICT round 3, item 2).

Shapes are kept small (capacity 64, depth 4) because the interpret-mode
lexN network compiles one XLA-CPU program per (depth, seq_bits) shape.
"""
import random
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.models import rseq, rseq_engine, tomb_gc
from crdt_tpu.models.oplog_engine import EngineFallback
from crdt_tpu.parallel import swarm

AD = rseq.GC_ADAPTER
CAP = 64
DEPTH = 4


def edited_state(seed, n_ops=30, n_writers=3, capacity=CAP, depth=DEPTH):
    """A Gc[RSeq] produced by a seeded random edit schedule."""
    rng = random.Random(seed)
    g = tomb_gc.wrap(rseq.empty(capacity, depth=depth), n_writers)
    w = rseq.SeqWriter(g.inner, rid=seed % n_writers)
    for k in range(n_ops):
        live = w._rows()
        if live and rng.random() < 0.35:
            w.delete_at(rng.randrange(len(live)))
        else:
            w.insert_at(rng.randint(0, len(live)), 1000 * seed + k)
    return g.replace(inner=w.state)


def diverged_pair(seed):
    """Two states that share history, then diverge — including a floor
    advance on one side only, so the suppression rule has work to do."""
    a, b = edited_state(seed), edited_state(seed + 17)
    st = jax.tree.map(lambda *xs: jnp.stack(xs), a, b)
    sw = tomb_gc.gc_round(
        swarm.make(st, jnp.ones(2, bool)), AD,
        rseq.empty(CAP, depth=DEPTH), engine="generic",
    )
    a2 = jax.tree.map(lambda x: x[0], sw.state)
    b2 = jax.tree.map(lambda x: x[1], sw.state)
    w = rseq.SeqWriter(a2.inner, rid=0,
                       seq_start=tomb_gc.next_seq(a2, AD, 0))
    for k in range(8):
        w.insert_at(0, 9000 + k)
    for _ in range(4):
        w.delete_at(0)
    return a2.replace(inner=w.state), b2


def assert_gc_equal(x, y):
    assert (np.asarray(x.inner.keys) == np.asarray(y.inner.keys)).all()
    assert (np.asarray(x.inner.elem) == np.asarray(y.inner.elem)).all()
    assert (np.asarray(x.inner.removed) == np.asarray(y.inner.removed)).all()
    assert (np.asarray(x.floor) == np.asarray(y.floor)).all()


@pytest.mark.parametrize("seed", [0, 1])
def test_pairwise_join_equivalence(seed):
    a, b = diverged_pair(seed)
    g_col, nu_col = rseq_engine.gc_join_checked(a, b)
    g_gen, nu_gen = tomb_gc.join_checked(a, b, AD)
    assert int(nu_col) == int(nu_gen)
    assert_gc_equal(g_col, g_gen)
    # commutativity carries over
    g_rev, nu_rev = rseq_engine.gc_join_checked(b, a)
    assert int(nu_rev) == int(nu_col)
    assert_gc_equal(g_rev, g_col)


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_barrier_equivalence_with_dead_lane():
    a, b = diverged_pair(3)
    c = edited_state(5)
    st = jax.tree.map(lambda *xs: jnp.stack(xs), a, b, c)
    alive = jnp.asarray([True, True, False])
    neutral = rseq.empty(CAP, depth=DEPTH)
    s_gen = tomb_gc.gc_round(swarm.make(st, alive), AD, neutral,
                             engine="generic")
    with warnings.catch_warnings():
        warnings.simplefilter("error", EngineFallback)  # no fallback allowed
        s_col = tomb_gc.gc_round(swarm.make(st, alive), AD, neutral)
    for l_gen, l_col in zip(jax.tree.leaves(s_gen.state),
                            jax.tree.leaves(s_col.state)):
        assert (np.asarray(l_gen) == np.asarray(l_col)).all()
    # the dead lane is untouched on both engines
    dead_gen = jax.tree.map(lambda x: x[2], s_gen.state)
    assert_gc_equal(dead_gen, c)


def test_fallback_is_loud():
    bad = tomb_gc.wrap(rseq.empty(96, depth=DEPTH), 3)  # 96: not a pow2
    st = jax.tree.map(lambda *xs: jnp.stack(xs), bad, bad)
    with pytest.warns(EngineFallback, match="power of two"):
        out = rseq_engine.gc_converge_swarm(
            swarm.make(st, jnp.ones(2, bool))
        )
    assert out is None
    with pytest.warns(EngineFallback, match="power of two"):
        g, nu = rseq_engine.gc_join_checked_auto(bad, bad)
    # the generic path served: result is still a correct (empty) join
    assert int(nu) == 0


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_soak_rides_columnar_engine():
    """The seq soak's default engine is the columnar one — a short sweep
    must pass with fallback warnings escalated to errors (proving every
    join and barrier actually rode the fused-kernel path)."""
    from crdt_tpu.harness.seq_soak import SeqSoakRunner

    with warnings.catch_warnings():
        warnings.simplefilter("error", EngineFallback)
        report = SeqSoakRunner(n=3, seed=11, capacity=CAP, engine="auto").run(30)
    assert report.steps == 30


@pytest.mark.slow  # interpret-mode e2e: minutes on the CPU tier-1 runner
def test_sharded_gc_converge_matches_generic():
    """Round-5 (round-4 verdict missing #1): the GC-aware converge under
    shard_map over the 8-device virtual mesh — per-lane floor planes
    crossing the all-gather — must be bit-identical to the single-device
    columnar converge AND to the generic tomb_gc tree reduction."""
    from crdt_tpu.parallel import mesh as mesh_lib

    states = [edited_state(s) for s in range(7)] + [edited_state(100)]
    # give some lanes a floor advance so suppression crosses the gather
    a, b = diverged_pair(11)
    states[0], states[1] = a, b
    st = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    alive = jnp.asarray([True] * 6 + [False, True])

    cg = rseq_engine.stack(st)
    m = mesh_lib.make_mesh(8)
    step = rseq_engine.sharded_gc_converge(
        m, depth=DEPTH, seq_bits=cg.col.seq_bits
    )
    out, max_nu = step(cg, alive)

    want, wnu = rseq_engine.gc_converge_checked(cg, alive, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(out.col.keys), np.asarray(want.col.keys)
    )
    np.testing.assert_array_equal(
        np.asarray(out.col.elem), np.asarray(want.col.elem)
    )
    np.testing.assert_array_equal(
        np.asarray(out.col.removed), np.asarray(want.col.removed)
    )
    np.testing.assert_array_equal(
        np.asarray(out.floor), np.asarray(want.floor)
    )
    assert int(max_nu) == int(wnu)

    # and against the generic gc_round convergence phase (floors + tables)
    neutral = rseq.empty(CAP, depth=DEPTH)
    s_gen = tomb_gc.gc_round(
        swarm.make(st, alive), AD, neutral, engine="generic"
    )
    g_out = rseq_engine.unstack(out)
    # gc_round also runs the floor-agreement/collect phase after
    # convergence; compare against its convergence-phase output by
    # re-running just the generic tree reduction
    jbc = jax.vmap(lambda x, y: tomb_gc.join_checked(x, y, AD))
    from crdt_tpu.ops import joins as joins_mod
    from crdt_tpu.parallel import swarm as swarm_mod

    neutral_g = tomb_gc.wrap(neutral, st.floor.shape[-1])
    state = joins_mod.pad_to_pow2(
        swarm_mod.mask_dead_with_neutral(st, alive, neutral_g), neutral_g
    )
    p = jax.tree.leaves(state)[0].shape[0]
    while p > 1:
        p //= 2
        lo = jax.tree.map(lambda x: x[:p], state)
        hi = jax.tree.map(lambda x: x[p: 2 * p], state)
        state, _ = jbc(lo, hi)
    top = jax.tree.map(lambda x: x[0], state)
    want_gen = swarm_mod.broadcast_where_alive(st, alive, top)
    want_gen = jax.tree.map(
        lambda conv, stale: jnp.where(
            alive.reshape((-1,) + (1,) * (conv.ndim - 1)), conv, stale
        ),
        want_gen, st,
    )
    for l_gen, l_col in zip(jax.tree.leaves(want_gen),
                            jax.tree.leaves(g_out)):
        assert (np.asarray(l_gen) == np.asarray(l_col)).all()
